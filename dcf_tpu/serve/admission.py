"""Admission control: bounded request queue, priority classes, deadlines.

The service's overload policy is decided HERE, at submit time, not
discovered later as memory pressure: the queue is bounded in queued
POINTS (requests are ragged — a bound in requests would let one giant
request soak the device for seconds while claiming a queue depth of 1),
and a submit that would exceed the bound is shed immediately with
``QueueFullError``.  A shed request costs the caller one exception and
zero device work — the cheapest possible failure in a loaded system.

Priority classes (ISSUE 6): every request carries a ``Priority`` —
``CRITICAL`` / ``NORMAL`` / ``BATCH`` — and overload sheds
lowest-class-first:

* a submit that would exceed the points bound may EVICT queued
  strictly-lower-class requests (lowest class first, newest first) to
  make room; evicted futures complete with ``QueueFullError``.  The
  eviction is all-or-nothing — nobody is evicted unless the incoming
  request then fits (shedding two requests to admit zero would be pure
  loss).
* **brownout** — a degraded-admission mode the service enters on
  sustained queue pressure or open circuit breakers (``serve.breaker``)
  and exports as the ``serve_brownout`` gauge — refuses ``BATCH``
  submits outright at the door, before they cost queue room.
* ``CRITICAL`` keeps the pre-priority semantics exactly: admitted
  whenever the bound allows (evicting lower classes if needed), never
  brownout-refused, never evicted (nothing outranks it).

Dispatch order stays FIFO (``take_group`` is priority-blind): classes
decide *who is shed*, not *who jumps the queue* — a reordering queue
would starve BATCH under permanent moderate load, whereas shed-only
priorities degrade it exactly when something is actually wrong.

Deadlines propagate as absolute clock values (the injectable serve clock,
``utils.benchtime.monotonic`` by default).  They are enforced at batch
formation: an expired request is completed with ``DeadlineExceededError``
and never reaches the device.  In-flight batches are never aborted — a
dispatched batch is at most one ``max_delay + eval`` old, and tearing
down a device dispatch mid-flight costs more than finishing it.

``ServeFuture`` is the result handle: ``result(timeout)`` blocks on a
``threading.Event`` (the service's worker thread completes it) and either
returns the uint8 [K, M, lam] share or raises the typed failure.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Callable

import numpy as np

from dcf_tpu.errors import DeadlineExceededError, QueueFullError, ShapeError
from dcf_tpu.serve.metrics import Metrics, labeled

__all__ = ["Priority", "parse_priority", "TenantSpec", "ServeFuture",
           "Request", "AdmissionQueue", "expire"]


class Priority(enum.IntEnum):
    """Request priority class; LOWER value = higher priority (sorting a
    mixed list ascending puts the most-protected traffic first)."""

    CRITICAL = 0
    NORMAL = 1
    BATCH = 2


def parse_priority(p) -> Priority:
    """``Priority`` | case-insensitive name -> ``Priority`` (the serve
    edge accepts both so CLI flags and loadgen specs stay strings)."""
    if isinstance(p, Priority):
        return p
    if isinstance(p, str):
        try:
            return Priority[p.upper()]
        except KeyError:
            pass
    # api-edge: documented priority-class contract at the serve edge
    raise ValueError(
        f"priority must be a Priority or one of "
        f"{[x.name.lower() for x in Priority]}, got {p!r}")


@dataclass(frozen=True)
class TenantSpec:
    """One network-edge tenant and its admission policy (ISSUE 12).

    Tenants map onto the EXISTING priority classes — the tenant table
    is a naming layer over the PR 6 shed/brownout policy, never a
    second policy: ``priority`` is the class every request from this
    tenant is admitted as (a request frame may self-DEMOTE below it —
    a gold tenant running an offline sweep can mark it BATCH — but can
    never self-promote above its tenant class).

    ``points_per_sec`` / ``burst_points`` configure the per-tenant
    token bucket the edge applies BEFORE the request touches the shared
    queue (``serve.edge.TokenBucket``): 0 points/s disables rate
    limiting for the tenant; ``burst_points`` is the bucket capacity
    (0 = one second of rate — a full-rate burst).  The bucket refuses
    with ``QueueFullError`` carrying the exact time-to-refill as its
    ``retry_after_s``.
    """

    name: str
    priority: Priority | str = Priority.NORMAL
    points_per_sec: float = 0.0
    burst_points: int = 0

    def __post_init__(self):
        if not self.name:
            # api-edge: tenant-table contract (the empty name is the
            # anonymous default-tenant spelling on the wire, never a
            # declarable tenant)
            raise ValueError("tenant name must be non-empty")
        # Normalize the class eagerly so a typo'd name dies at config
        # time, not per-request on a serving thread.
        object.__setattr__(self, "priority", parse_priority(self.priority))
        if self.points_per_sec < 0:
            # api-edge: tenant-table contract (0 = unlimited)
            raise ValueError(
                f"tenant {self.name!r}: points_per_sec must be >= 0, "
                f"got {self.points_per_sec}")
        if self.burst_points < 0:
            # api-edge: tenant-table contract (0 = one second of rate)
            raise ValueError(
                f"tenant {self.name!r}: burst_points must be >= 0, "
                f"got {self.burst_points}")


class ServeFuture:
    """Completion handle for one submitted request."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, value: np.ndarray) -> None:
        self._value = value
        self._event.set()

    def set_exception(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        """The request's uint8 [K, M, lam] share, or its typed failure.
        Raises ``TimeoutError`` if the service has not completed the
        request within ``timeout`` seconds (the request stays live)."""
        if not self._event.wait(timeout):
            # dcflint: disable=typed-error a result-wait timeout means
            # "not done yet", not a framework failure: the builtin
            # TimeoutError is the documented contract (and deliberately
            # NOT DeadlineExceededError, which means "dropped undone")
            raise TimeoutError("request not completed yet")
        error = self._error  # re-raise of the stored completion failure
        if error is not None:
            raise error
        return self._value


class Request:
    """One accepted request: points for one (key_id, party) pair."""

    __slots__ = ("key_id", "b", "xs", "m", "deadline", "enq_t", "future",
                 "priority")

    def __init__(self, key_id: str, b: int, xs: np.ndarray,
                 deadline: float | None, enq_t: float,
                 priority: Priority = Priority.NORMAL):
        self.key_id = key_id
        self.b = int(b)
        self.xs = xs
        self.m = int(xs.shape[0])
        self.deadline = deadline
        self.enq_t = enq_t
        self.priority = priority
        self.future = ServeFuture()

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def __repr__(self) -> str:  # points are caller data: shapes only
        return (f"Request(key_id={self.key_id!r}, b={self.b}, m={self.m}, "
                f"priority={self.priority.name}, "
                f"deadline={self.deadline})")


class AdmissionQueue:
    """FIFO bounded-points queue with group extraction for the batcher.

    Thread-safe; ``cond`` is the wakeup signal the worker waits on
    (notified on every accepted submit and on ``close``).
    """

    def __init__(self, max_queued_points: int,
                 metrics: Metrics | None = None, *,
                 shed_retry_after_s: float | None = None,
                 brownout_retry_after_s: float | None = None):
        if max_queued_points < 1:
            # api-edge: constructor bound contract
            raise ValueError(
                f"max_queued_points must be >= 1, got {max_queued_points}")
        self.max_queued_points = int(max_queued_points)
        # Retry-after hints (ISSUE 12): what a shed caller is told to
        # back off for.  Overload sheds carry ``shed_retry_after_s``
        # (the service passes ~one coalescing drain interval — the
        # soonest the queue could plausibly have room again); brownout
        # refusals carry ``brownout_retry_after_s`` (the service passes
        # ``brownout_clear_s`` — the calm the hysteresis controller
        # needs before it re-admits BATCH).  Draining/closed refusals
        # carry no hint: the service is not coming back.
        self.shed_retry_after_s = shed_retry_after_s
        self.brownout_retry_after_s = brownout_retry_after_s
        self._metrics = metrics if metrics is not None else Metrics()
        self.cond = threading.Condition()
        # guarded-by: cond
        self._reqs: list[Request] = []
        # guarded-by: cond
        self._points = 0
        # guarded-by: cond
        self._closed = False
        # guarded-by: cond
        self._brownout = False
        self._g_depth = self._metrics.gauge("serve_queue_depth")
        self._g_points = self._metrics.gauge("serve_queue_points")
        self._g_brownout = self._metrics.gauge("serve_brownout")
        self._c_shed = self._metrics.counter("serve_shed_total")
        self._c_accepted = self._metrics.counter("serve_requests_total")
        self._c_accepted_points = self._metrics.counter("serve_points_total")
        self._c_brownout_refused = self._metrics.counter(
            "serve_brownout_refusals_total")
        self._c_evicted = self._metrics.counter("serve_queue_evicted_total")
        # Pre-registered per-class series: a snapshot always carries all
        # three keys (a missing class reads as "never shed" — tests and
        # the chaos harness assert on exact zeros).
        self._c_shed_by = {
            pr: self._metrics.counter(labeled(
                "serve_shed_by_class_total", priority=pr.name.lower()))
            for pr in Priority}
        self._c_evicted_by = {
            pr: self._metrics.counter(labeled(
                "serve_queue_evicted_by_class_total",
                priority=pr.name.lower()))
            for pr in Priority}

    def set_brownout(self, on: bool) -> None:
        """Flip the brownout gate (the SERVICE owns the entry/exit
        policy — sustained pressure with hysteresis; the queue just
        enforces the refusal)."""
        on = bool(on)
        # dcflint: disable=guarded-by hot-path no-op probe (see below):
        # a torn/stale read at worst takes or skips the condvar once;
        # the guarded write below re-checks nothing because same-value
        # sets are idempotent.
        if self._brownout == on:
            # Hot-path no-op: the service calls this on every submit
            # and pump iteration while pressure holds; don't take the
            # queue condvar to rewrite an unchanged gauge.  (Unlocked
            # read is benign: concurrent same-value sets are idempotent.)
            return
        with self.cond:
            self._brownout = on
            self._g_brownout.set(int(on))

    @property
    def brownout(self) -> bool:
        # dcflint: disable=guarded-by monitoring snapshot: a single
        # bool read (atomic under the GIL), advisory by contract —
        # admission decisions re-read it under the condvar in put()
        return self._brownout

    def _shed(self, req: Request) -> None:
        self._c_shed.inc()
        self._c_shed_by[req.priority].inc()

    # holds-lock: cond
    def _pick_victims(self, req: Request) -> list[Request] | None:
        """Queued strictly-lower-class requests whose eviction makes
        ``req`` fit — lowest class first, newest first within a class —
        or ``None`` when no such set exists (all-or-nothing: nobody is
        evicted for an admit that still fails)."""
        need = self._points + req.m - self.max_queued_points
        victims: list[Request] = []
        # Newest-first = highest queue index (enq_t ties under a fake
        # clock; position is the unambiguous arrival order).
        candidates = [r for _, r in sorted(
            ((i, r) for i, r in enumerate(self._reqs)
             if r.priority > req.priority),
            key=lambda ir: (-ir[1].priority, -ir[0]))]
        for r in candidates:
            if need <= 0:
                break
            victims.append(r)
            need -= r.m
        return victims if need <= 0 else None

    def put(self, req: Request) -> None:
        """Admit or shed ``req`` (QueueFullError on overload/brownout/
        shutdown); may evict queued lower-class requests to admit it."""
        if req.m > self.max_queued_points:
            # Not an overload: this request can NEVER be admitted, so a
            # "back off and retry" QueueFullError would send the caller
            # into a futile loop — it is a size-contract violation.
            raise ShapeError(
                f"request of {req.m} points exceeds the admission bound "
                f"max_queued_points={self.max_queued_points} outright; "
                "split the request (or raise the bound)")
        victims: list[Request] = []
        with self.cond:
            if self._closed:
                # Shutdown rejections count as shed too: loadgen counts
                # them off the same QueueFullError, and the two numbers
                # land in the same RESULTS_serve line — they must agree.
                self._shed(req)
                raise QueueFullError(
                    "service is draining/closed; no new requests")
            if self._brownout and req.priority is Priority.BATCH:
                self._shed(req)
                self._c_brownout_refused.inc()
                raise QueueFullError(
                    "brownout: the service is shedding BATCH-class load "
                    "(sustained queue pressure or an open circuit "
                    "breaker); back off and retry, or raise the class",
                    retry_after_s=self.brownout_retry_after_s)
            if self._points + req.m > self.max_queued_points:
                picked = self._pick_victims(req)
                if picked is None:
                    self._shed(req)
                    raise QueueFullError(
                        f"admission queue full: {self._points} points "
                        f"queued + {req.m} requested > bound "
                        f"{self.max_queued_points}; back off and retry",
                        retry_after_s=self.shed_retry_after_s)
                victims = picked
                evicted = set(map(id, victims))
                self._reqs = [r for r in self._reqs
                              if id(r) not in evicted]
                self._points -= sum(r.m for r in victims)
                self._c_evicted.inc(len(victims))
                for r in victims:
                    self._c_evicted_by[r.priority].inc()
                    # Evictions are sheds delivered late: count them in
                    # the same totals loadgen reconciles against.
                    self._shed(r)
            self._reqs.append(req)
            self._points += req.m
            self._c_accepted.inc()
            self._c_accepted_points.inc(req.m)
            self._sync_gauges()
            self.cond.notify_all()
        # Complete evicted futures outside the lock: result() waiters
        # wake immediately and must not contend the admission path.
        for r in victims:
            r.future.set_exception(QueueFullError(
                f"evicted from the admission queue: a higher-priority "
                f"submit needed the room ({r!r})",
                retry_after_s=self.shed_retry_after_s, evicted=True))

    def close(self) -> None:
        """Stop admitting; queued requests remain for draining."""
        with self.cond:
            self._closed = True
            self.cond.notify_all()

    @property
    def closed(self) -> bool:
        # dcflint: disable=guarded-by monitoring snapshot: one atomic
        # bool read; the admit path re-checks under the condvar
        return self._closed

    def __len__(self) -> int:
        # dcflint: disable=guarded-by monitoring snapshot: len() of a
        # list the GIL keeps internally consistent; depth gauges and
        # tests tolerate one-update staleness by contract
        return len(self._reqs)

    @property
    def points(self) -> int:
        # dcflint: disable=guarded-by monitoring snapshot: one atomic
        # int read, used for gauges/pressure sampling only — admission
        # re-reads it under the condvar
        return self._points

    def oldest_enq_t(self) -> float | None:
        with self.cond:
            return self._reqs[0].enq_t if self._reqs else None

    def take_expired(self, now: float) -> list[Request]:
        """Remove and return every queued request whose deadline passed
        (the caller completes them with ``DeadlineExceededError``)."""
        with self.cond:
            expired = [r for r in self._reqs if r.expired(now)]
            if expired:
                self._reqs = [r for r in self._reqs if not r.expired(now)]
                self._points = sum(r.m for r in self._reqs)
                self._sync_gauges()
            return expired

    def take_group(self, max_batch_points: int) -> list[Request]:
        """Remove and return the head request's (key_id, party) group:
        same-group requests in FIFO order until one does not fit in
        ``max_batch_points`` — at which point the group CLOSES, so a
        later-submitted smaller request can never jump an earlier one
        (per-request latency stays FIFO within a group).  The head
        request is always taken, however large — the batcher splits it.
        Other groups keep their order."""
        with self.cond:
            if not self._reqs:
                return []
            head = self._reqs[0]
            group, rest, total = [head], [], head.m
            closed_group = False
            for r in self._reqs[1:]:
                if (r.key_id, r.b) == (head.key_id, head.b) \
                        and not closed_group:
                    if total + r.m <= max_batch_points:
                        group.append(r)
                        total += r.m
                        continue
                    closed_group = True  # preserve FIFO within the group
                rest.append(r)
            self._reqs = rest
            self._points = sum(r.m for r in rest)
            self._sync_gauges()
            return group

    def fail_all(self, make_error: Callable[[], BaseException]) -> int:
        """Drop every queued request, completing each with a fresh error
        (non-drain shutdown).  Returns the count."""
        with self.cond:
            reqs, self._reqs, self._points = self._reqs, [], 0
            self._sync_gauges()
        for r in reqs:
            r.future.set_exception(make_error())
        return len(reqs)

    # holds-lock: cond
    def _sync_gauges(self) -> None:
        self._g_depth.set(len(self._reqs))
        self._g_points.set(self._points)


def expire(reqs: list[Request], metrics: Metrics) -> None:
    """Complete ``reqs`` with DeadlineExceededError (and count them)."""
    if reqs:
        metrics.counter("serve_deadline_expired_total").inc(len(reqs))
    for r in reqs:
        r.future.set_exception(DeadlineExceededError(
            f"deadline passed before dispatch ({r!r})"))
