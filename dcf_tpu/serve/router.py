"""Pod-scale routing tier (ISSUE 13): a zero-copy DCFE router over a
rendezvous-hashed shard ring.

``DcfRouter`` is the distributed half of the serving tier.  A pod is N
independent shard processes — each the existing crash-safe,
breaker-guarded, pool-fed single-host unit (``DcfService`` +
``EdgeServer``, ISSUE 8/6/11/12) — and the router is the one process
clients talk to.  It speaks DCFE on BOTH sides:

* **downstream** it IS an ``EdgeServer`` target: the router exposes
  the service-like surface (``n_bytes``, ``_clock``, ``metrics``,
  ``config.tenants``, ``submit_bytes``) so the PR 12 edge front — its
  frame codecs, tenant table, token buckets, per-connection
  containment and wire fuzz discipline — fronts the router UNCHANGED.
  Tenancy therefore lives at the pod door: the router's tenant table
  admits and class-caps requests once, and the shard links ride the
  open edge (or a TLS-pinned one — see below);
* **upstream** it forwards through ``EdgeClientPool`` connections, one
  pool per shard.  Forwarding is HEADER-DECODE ONLY: the edge front
  decodes the request header and hands this router the payload as a
  ``memoryview`` of the received frame buffer, and
  ``EdgeClient.submit_bytes`` relays exactly that view through the
  scatter-gather send — the packed points are never re-materialized,
  so PR 12's zero-copy ingest contract holds end to end across two
  hops (the shard's batcher gathers straight from bytes that were
  DMA'd off the router's socket).  Responses relay the same way:
  the share planes decoded off the shard connection are a view of its
  receive buffer, and ``encode_share`` hands that buffer to the
  downstream ``sendmsg``.

Placement is the ``serve.shardmap`` rendezvous ring: ``owner(key_id)``
serves the key, ``ranked(key_id)[1]`` is its replica.  Provisioning
mirrors the same ranking (``ShardMap.placement``): a durable key's
DCFK frame is written — ``KeyStore`` discipline, atomic publish,
generation preserved — into the owner's AND the replica's stores, so
the host failover lands on has already restored the key at warm-start
(``restore_keys()``) with the generation the owner registered it
under.

Failover consumes the EXISTING typed taxonomy as its signal — the
router invents no second health protocol:

* a TRANSPORT death (connect refused, dark target, a send/read that
  failed — ``BackendUnavailableError`` with no ``wire_code``) and a
  shard-side **breaker-open** (``E_CIRCUIT_OPEN``) or **overload/
  brownout** (``E_QUEUE_FULL``, non-evicted) error frame all mark the
  shard SUSPECT until the hint's ``retry_after_s`` (or
  ``suspect_cooldown_s``) elapses on the injectable clock;
* while the owner is suspect, **CRITICAL** traffic fails over to the
  key's replica shard — which serves the durably replicated frame,
  generation preserved — and a CRITICAL request that watched its
  forward die fails over inline, once, before reporting anything;
* **everything else is refused typed** with ``CircuitOpenError``
  carrying the remaining suspect time as ``retry_after_s`` — the same
  fail-fast contract the per-host breaker board gives a single shard,
  lifted to the ring;
* every OTHER typed outcome (unknown key, ``ShapeError``, deadline,
  ``StaleStateError`` from a hot-swap racing a forwarded eval —
  ``E_STALE`` keeps it distinguishable on the wire) passes through
  untouched: key-level outcomes are the caller's, not routing signals.

Self-healing (ISSUE 14): the per-request suspicion above is the FAST
signal; the ``serve.health.HealthProber`` is the control plane layered
on top — a periodic DCFE PING per shard through the same pools, with
UP -> SUSPECT -> DOWN -> UP hysteresis (``probe_fail_n`` /
``probe_recover_m``):

* a prober-SUSPECT shard routes exactly like a request-suspect one
  (merged in ``_routable_remaining``; the metrics keep the two
  distinguishable — ``router_suspected_total`` vs
  ``router_health_state``/``router_probe_failures_total``);
* a DOWN shard is dropped from the placement walk for EVERY class:
  each victim key's replica is PROMOTED to acting owner
  (``router_promoted_forwards_total`` — no keys move, rendezvous
  already pinned the successor), so NORMAL/BATCH traffic keeps
  serving instead of waiting out refusal cooldowns;
* recovery is GATED: the DOWN -> UP transition runs the anti-entropy
  pass (``serve.replicate.Replicator.anti_entropy`` — digest
  exchange, strictly-newer pulls, monotonic-generation fence) before
  the shard is re-admitted, and the UP transition clamps the pool's
  dial backoff and clears stale request suspicion.

Live registrations (ISSUE 14): ``register_frame``/``register_key``
fan a DCFK frame out across the ring — the owner MINTS the
generation, replicas apply it preserved, and the fence
(``StaleStateError``/``E_STALE``) makes an old partition side
structurally unable to roll a key back.  ``KeyStore.replicate_to``
remains the durable twin.  ``set_ring`` swaps membership atomically
and FORGETS removed hosts' state and metric series (bounded
cardinality under host churn).

Cross-host hot-swap needs no new machinery: re-registering a key on
its shard bumps the registry generation there, and a forwarded eval
whose group snapshot predates the swap fails ``StaleStateError``
exactly as an in-process one would (PR 5's guard) — the router relays
the typed error and never pairs stale images.

Mesh co-evaluation (ISSUE 18): next to "route" — one host, one key —
the router speaks a second dispatch mode, "co-evaluate": ONE batch
laid across EVERY mesh worker.  ``set_mesh`` forms a ``MeshGroup``
(``serve.meshgroup`` — device placement, deliberately separate from
the ring's key placement) fenced at the current ring epoch;
``register_mesh_key`` makes a key pod-resident (owner mints through
the ring walk, every other worker applies preserved); a qualifying
request (``co_eval`` policy x ``co_eval_min_points`` threshold) is
SCATTERED as zero-copy sub-views of the same frame buffer — each
worker takes a 32-aligned contiguous point slice through the existing
DCFE relay — and the shares are GATHERED back in plan order.  The
mesh is an optimization, never a liability: a worker death, a fenced
epoch, or a missing group degrades the whole batch to route-mode —
counted ``router_mesh_degraded_total``, warned
``BackendFallbackWarning``, zero lost keys — unless the caller FORCED
the mesh (``co_eval="always"``), who gets ``MeshUnavailableError``
typed with the probe interval as the hint.  Fault seam:
``faults.fire("mesh.collective")`` at each co-evaluated dispatch.

TLS (ISSUE 13 satellite): give the router ``tls_*`` client knobs and
each shard's ``EdgeServer`` a cert (plus ``tls_client_ca`` to PIN the
router's client cert) and the router<->shard links are encrypted and
mutually authenticated; the pod door takes the same server knobs
through the router's ``config``.

Clocking: suspicion math runs on the injectable clock (dcflint
determinism).  All state is per-router-process; two routers over the
same ring agree on placement by construction (the ring is a pure
function) and converge on health independently — suspicion is local
observation, not consensus.
"""

from __future__ import annotations

import threading
import warnings

import numpy as np

from dcf_tpu.errors import (
    BackendFallbackWarning,
    BackendUnavailableError,
    CircuitOpenError,
    MeshUnavailableError,
    ShapeError,
)
from dcf_tpu.serve.admission import Priority, parse_priority
from dcf_tpu.serve.edge import (
    E_CIRCUIT_OPEN,
    E_EPOCH,
    E_QUEUE_FULL,
    EdgeClientPool,
    EdgeServer,
)
from dcf_tpu.serve.health import DOWN, SUSPECT, HealthProber
from dcf_tpu.serve.meshgroup import MeshGroup
from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.serve.replicate import Replicator
from dcf_tpu.serve.service import ServeConfig
from dcf_tpu.serve.shardmap import ShardMap, ShardSpec
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["DcfRouter"]


def _suspect_signal(exc: BaseException) -> bool:
    """Does ``exc`` indict the SHARD (vs the request)?  Transport
    death carries no ``wire_code``; breaker-open and overload/brownout
    arrive as coded error frames.  An EVICTED QueueFullError is a
    priority-pressure outcome for one request, not host sickness, and
    ``E_RATE_LIMITED`` (a tenant bucket on the shard link) would be
    router misconfiguration — neither marks a shard suspect."""
    code = getattr(exc, "wire_code", None)
    if code is None:
        return isinstance(exc, BackendUnavailableError)
    if code == E_CIRCUIT_OPEN:
        return True
    return code == E_QUEUE_FULL and not getattr(exc, "evicted", False)


class _RelayFuture:
    """The future a routed submit returns: waits on the forwarded
    request and owns the response-time half of the failover policy.
    The work runs on the WAITER's thread (the edge writer streaming
    this future, or an in-process caller) — the router spawns no
    per-request threads."""

    __slots__ = ("_router", "_inner", "_target", "_args")

    def __init__(self, router: "DcfRouter", inner, target: ShardSpec,
                 args: tuple | None):
        self._router = router
        self._inner = inner
        self._target = target
        self._args = args  # (key_id, data, m, b, deadline_ms, pri),
        # or None once failover is spent; holding ``data`` here is safe
        # because the edge front keeps the frame buffer alive until
        # this future completes

    def done(self) -> bool:
        return self._inner.done()

    def result(self, timeout: float | None = None) -> np.ndarray:
        # One deadline across failovers: a caller's result(5) must
        # return or raise within ~5s even if the wait is split across
        # the owner and the replica — the failover wait gets the
        # REMAINING time, not a fresh budget.
        deadline = None if timeout is None \
            else self._router._clock() + timeout
        while True:
            remaining = None if deadline is None else max(
                deadline - self._router._clock(), 0.0)
            try:
                return self._inner.result(remaining)
            except TimeoutError:
                raise
            except Exception as e:  # fallback-ok: classified by the
                # router — a shard-indicting failure marks it suspect
                # (and may fail over); everything else re-raises as
                # the caller's typed outcome.  The loop runs the
                # FAILOVER target's outcome through the same
                # classification (args spent, so at most one inline
                # re-route): a transport death on the replica must
                # also mark it suspect and surface hinted, never
                # escape bare.
                retry = self._router._on_forward_failure(
                    self._target, e, self._args)
                if retry is None:
                    raise
                self._inner, self._target = retry
                self._args = None  # one inline failover per request


class _MeshFuture:
    """The future a co-evaluated submit returns: waits on every
    scattered slice IN PLAN ORDER and concatenates the share planes
    back along the point axis.  Owns the response-time half of the
    degradation policy: a worker that dies mid-batch (shard-indicting
    signal) is marked suspect and — unless the caller forced the mesh
    — the WHOLE batch is re-routed once through route-mode (zero lost
    keys; the surviving workers' partial shares are discarded, not
    stitched to a re-evaluation).  Key-level outcomes pass through
    verbatim, same contract as the relay future."""

    __slots__ = ("_router", "_parts", "_args")

    def __init__(self, router: "DcfRouter", parts, args: tuple | None):
        self._router = router
        self._parts = parts  # [(inner, ShardSpec, MeshSlice)], plan order
        self._args = args  # (key_id, data, m, b, deadline_ms, pri),
        # or None when degradation is spent / forced-mesh (no re-route)

    def done(self) -> bool:
        return all(inner.done() for inner, _s, _sl in self._parts)

    def result(self, timeout: float | None = None) -> np.ndarray:
        # One deadline across the gather AND a possible degradation:
        # a caller's result(5) budget is shared by every slice wait
        # and the route-mode re-submission, not multiplied by them.
        deadline = None if timeout is None \
            else self._router._clock() + timeout
        shares = []
        for inner, spec, _sl in self._parts:
            remaining = None if deadline is None else max(
                deadline - self._router._clock(), 0.0)
            try:
                shares.append(inner.result(remaining))
            except TimeoutError:
                raise
            except Exception as e:  # fallback-ok: classified below —
                # worker death degrades (or surfaces typed when the
                # mesh was forced); key-level outcomes are the
                # caller's, verbatim
                if not _suspect_signal(e):
                    if getattr(e, "wire_code", None) == E_EPOCH:
                        self._router._c_stale_epoch.inc()
                    raise
                self._router.mark_suspect(
                    spec.host_id, getattr(e, "retry_after_s", None))
                if self._args is None:
                    raise MeshUnavailableError(
                        f"mesh worker {spec.host_id!r} died mid-batch "
                        f"({type(e).__name__}: {e})",
                        retry_after_s=self._router.health.interval_s
                    ) from e
                key_id, data, m, b, deadline_ms, pri = self._args
                self._router._mesh_degrade(
                    f"worker {spec.host_id!r} died mid-batch", e)
                fut = self._router._submit_route(
                    key_id, data, m, b, deadline_ms, pri)
                remaining = None if deadline is None else max(
                    deadline - self._router._clock(), 0.0)
                return fut.result(remaining)
        return np.concatenate(shares, axis=1)


class DcfRouter:
    """DCFE router over a shard ring (see the module docstring).

    ``shards``: a ``ShardMap`` or an iterable of ``ShardSpec``.
    ``n_bytes``: the pod's packed point width (every shard serves the
    same geometry; the router cannot discover it over the wire).
    ``tenants``: the pod-door tenant table (``admission.TenantSpec``)
    — consumed by the fronting ``EdgeServer`` exactly as a single
    shard's would be.  ``replicas``: how many ranking successors hold
    a key's replicated frame (the failover walk goes exactly that
    deep).  ``pool_size``: connections per shard link.  ``tls_*``:
    client-side TLS for the shard links (``tls_cert``/``tls_key`` =
    the router's client cert for pinned shards).

    ``probe_interval_s`` / ``probe_timeout_s`` / ``probe_fail_n`` /
    ``probe_recover_m`` (ISSUE 14): the health prober's cadence and
    hysteresis — ``start_health()`` runs it as a thread, tests drive
    ``health.pump()`` deterministically.  ``local_tag`` names this
    router on the ``net.partition`` fault seam.

    ``co_eval`` / ``co_eval_min_points`` (ISSUE 18): the co-evaluate
    dispatch policy.  ``"auto"`` (default) scatters a request across
    the mesh group when one is formed AND the batch reaches
    ``co_eval_min_points`` (the measured crossover — see ``pod_bench
    --mesh``), degrading to route-mode on any mesh trouble;
    ``"never"`` disables the mesh path; ``"always"`` forces it and
    surfaces mesh trouble typed (``MeshUnavailableError``).

    ``start(host, port)`` fronts the router with its own
    ``EdgeServer`` (DCFE downstream); in-process callers can skip it
    and drive ``submit``/``submit_bytes``/``evaluate`` directly (the
    loadgen's router-target mode).  ``register_key``/``register_frame``
    fan a live registration across the ring; ``set_ring`` swaps
    membership and forgets removed hosts' state."""

    def __init__(self, shards, *, n_bytes: int, tenants: tuple = (),
                 clock=monotonic, metrics: Metrics | None = None,
                 replicas: int = 1, suspect_cooldown_s: float = 1.0,
                 pool_size: int = 2, connect_timeout: float = 5.0,
                 reconnect_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 max_frame_bytes: int = 256 << 20, tls: bool = False,
                 tls_ca: str = "", tls_cert: str = "",
                 tls_key: str = "", probe_interval_s: float = 0.25,
                 probe_timeout_s: float | None = None,
                 probe_fail_n: int = 3, probe_recover_m: int = 2,
                 co_eval: str = "auto",
                 co_eval_min_points: int = 4096,
                 local_tag: str = "router"):
        self.map = shards if isinstance(shards, ShardMap) \
            else ShardMap(shards)
        if replicas < 0:
            # api-edge: router config contract
            raise ValueError(f"replicas must be >= 0, got {replicas}")
        if suspect_cooldown_s <= 0:
            # api-edge: router config contract — a zero cooldown would
            # mark-and-forget in the same instant, disabling failover
            raise ValueError(
                f"suspect_cooldown_s must be > 0, "
                f"got {suspect_cooldown_s}")
        if co_eval not in ("auto", "never", "always"):
            # api-edge: router config contract
            raise ValueError(
                f"co_eval must be 'auto', 'never' or 'always', "
                f"got {co_eval!r}")
        if co_eval_min_points < 1:
            # api-edge: router config contract
            raise ValueError(
                f"co_eval_min_points must be >= 1, "
                f"got {co_eval_min_points}")
        self.co_eval = co_eval
        self.co_eval_min_points = int(co_eval_min_points)
        # The co-evaluation group (ISSUE 18): formed by ``set_mesh``,
        # consulted by the dispatch policy, epoch-fenced at every
        # scatter.  None = route-mode only.
        self.mesh_group: MeshGroup | None = None
        self.n_bytes = int(n_bytes)
        self.replicas = int(replicas)
        self.suspect_cooldown_s = float(suspect_cooldown_s)
        self._clock = clock
        self.metrics = metrics if metrics is not None else Metrics()
        # The service-like config the fronting EdgeServer reads: the
        # tenant table is the POD door's admission policy.
        self.config = ServeConfig(tenants=tuple(tenants))
        self._lock = threading.Lock()
        self._suspect_until: dict[str, float] = {}
        # One kwargs dict so set_ring-created pools match construction-
        # time ones exactly (two pool builders would drift).
        self.local_tag = str(local_tag)
        self._pool_kwargs = dict(
            n_bytes=self.n_bytes, size=pool_size, clock=clock,
            connect_timeout=connect_timeout,
            reconnect_backoff_s=reconnect_backoff_s,
            max_backoff_s=max_backoff_s,
            max_frame_bytes=max_frame_bytes, tls=tls, tls_ca=tls_ca,
            tls_cert=tls_cert, tls_key=tls_key)
        self._pools = {s.host_id: self._make_pool(s)
                       for s in self.map.hosts()}
        self.edge: EdgeServer | None = None
        m = self.metrics
        self._c_forwards = {
            s.host_id: m.counter(labeled("router_forwards_total",
                                         shard=s.host_id))
            for s in self.map.hosts()}
        self._c_suspected = {
            s.host_id: m.counter(labeled("router_suspected_total",
                                         shard=s.host_id))
            for s in self.map.hosts()}
        self._c_failovers = m.counter("router_failovers_total")
        self._c_refused = m.counter("router_suspect_refusals_total")
        self._c_promoted = m.counter("router_promoted_forwards_total")
        self._c_down_refused = m.counter("router_down_refusals_total")
        self._g_suspects = m.gauge("router_suspect_shards")
        # Mesh co-evaluation series (ISSUE 18): dispatches that took
        # the mesh path, batches degraded back to route-mode, keys
        # made pod-resident, and the formed group's width.
        self._c_co_evals = m.counter("router_co_evals_total")
        self._c_mesh_degraded = m.counter("router_mesh_degraded_total")
        self._c_mesh_registered = m.counter(
            "router_mesh_registered_total")
        self._g_mesh_workers = m.gauge("router_mesh_workers")
        # The self-healing control plane (ISSUE 14): live-registration
        # fan-out + anti-entropy over the SAME pools the forwards use,
        # and the active health prober whose DOWN/UP transitions drive
        # promotion and gated re-admission (see the module docstring).
        # Ring epoch (ISSUE 15, ``serve.membership``): the monotonic
        # membership-commit counter this router routes under.  0 =
        # unfenced (a standalone router that never saw a membership
        # change) — frames then carry epoch 0 and shards skip the
        # check.  ``set_ring(..., epoch=)`` is the only writer; every
        # forward, registration fan-out and probe carries the value.
        self.ring_epoch = 0
        self._g_epoch = m.gauge("router_ring_epoch")
        self._c_stale_epoch = m.counter("router_stale_epoch_total")
        self.replicator = Replicator(
            self._pools, lambda: self.map, replicas=self.replicas,
            metrics=self.metrics,
            epoch_source=lambda: self.ring_epoch)
        self.health = HealthProber(
            self._pools, interval_s=probe_interval_s,
            timeout_s=probe_timeout_s, fail_n=probe_fail_n,
            recover_m=probe_recover_m, clock=clock,
            metrics=self.metrics, recover_gate=self._recover_gate,
            on_transition=self._on_health_transition,
            epoch_source=lambda: self.ring_epoch)

    def _make_pool(self, spec: ShardSpec) -> EdgeClientPool:
        return EdgeClientPool(spec.host, spec.port,
                              tags=(self.local_tag, spec.host_id),
                              **self._pool_kwargs)

    # -- health -------------------------------------------------------

    def _on_health_transition(self, ev) -> None:
        """React to a prober transition (ISSUE 14).  On UP: clamp the
        pool's dial backoff (satellite: a pool whose target was DOWN
        for a long time must not wait out its accumulated exponential
        backoff after health CONFIRMED recovery) and clear the
        request-signal suspicion — a probe-confirmed recovery outranks
        a stale per-request cooldown.  (Request suspicion raised while
        the prober still says UP is deliberately untouched: no
        transition fires, so the cooldown holds — the two signals
        disagree in the conservative direction.)"""
        if ev.to != "up":
            return
        pool = self._pools.get(ev.host_id)
        if pool is not None:
            pool.reset_backoff()
        with self._lock:
            self._suspect_until.pop(ev.host_id, None)
            now = self._clock()
            self._g_suspects.set(sum(
                1 for t in self._suspect_until.values() if t > now))

    def _recover_gate(self, host_id: str) -> bool:
        """The prober's DOWN -> UP gate: the anti-entropy pass
        (``serve.replicate``).  A shard is re-admitted only after it
        converged with every peer the prober does not itself hold
        DOWN — re-admitting earlier could serve stale generations,
        the silent-wrong-answer partition bug."""
        try:
            self.replicator.anti_entropy(
                host_id,
                peer_ok=lambda h: self.health.state(h) != DOWN)
        except Exception:  # fallback-ok: the prober counts the gate
            # failure and keeps the shard DOWN; the next recover_m
            # window retries
            return False
        return True

    def start_health(self) -> "DcfRouter":
        """Start the active prober thread (production mode; tests
        drive ``self.health.pump()`` deterministically instead)."""
        self.health.start()
        return self

    def loads(self) -> dict:
        """The freshest per-shard ``edge.LoadSample`` by host id, as
        sampled off the prober's PING/PONG round trips (ISSUE 16).
        ``None`` means the shard answers probes but exposes no load
        surface (a pre-16 shard); absent means it never answered.
        The demand feed the capacity controller
        (``serve.capacity``) aggregates — exposed here so operators
        read pod load where they already read pod health."""
        return self.health.loads()

    def suspect_remaining(self, host_id: str) -> float:
        """Seconds of suspicion left for ``host_id`` (0 = trusted).
        The REQUEST-signal cooldown only; the prober's states are read
        via ``self.health`` (the two are merged by the routing walk in
        ``_routable_remaining``, and distinguishable in the metrics:
        ``router_suspected_total`` counts request signals,
        ``router_health_state``/``router_probe_failures_total`` the
        probe plane)."""
        now = self._clock()
        with self._lock:
            return max(self._suspect_until.get(host_id, 0.0) - now, 0.0)

    def _routable_remaining(self, host_id: str) -> float:
        """The merged do-not-route window: the request-signal cooldown
        OR the prober's SUSPECT state (hinted at one probe interval —
        the next round resolves it either way)."""
        remaining = self.suspect_remaining(host_id)
        if self.health.state(host_id) == SUSPECT:
            remaining = max(remaining, self.health.interval_s)
        return remaining

    def mark_suspect(self, host_id: str,
                     for_s: float | None = None) -> None:
        """Mark a shard suspect for ``for_s`` seconds (default: the
        router's cooldown).  Extends, never shortens — two signals
        racing must not let the later, shorter hint re-admit early."""
        until = self._clock() + (self.suspect_cooldown_s
                                 if for_s is None else max(for_s, 0.0))
        with self._lock:
            if until > self._suspect_until.get(host_id, 0.0):
                self._suspect_until[host_id] = until
            now = self._clock()
            self._g_suspects.set(sum(
                1 for t in self._suspect_until.values() if t > now))
        c = self._c_suspected.get(host_id)
        if c is not None:
            c.inc()

    def _on_forward_failure(self, target: ShardSpec,
                            exc: BaseException, args):
        """Classify one forwarded request's failure (from the relay
        future's wait, on the waiter's thread).  Returns ``None`` to
        re-raise ``exc`` (possibly converted), or ``(inner, target)``
        for an inline CRITICAL failover that was successfully
        re-submitted."""
        if not _suspect_signal(exc):
            if getattr(exc, "wire_code", None) == E_EPOCH:
                # The shard told us OUR ring is stale (a membership
                # commit we have not applied): counted, passed through
                # verbatim — the hinted typed refusal is the caller's
                # signal, and refreshing the ring is the operator's
                # (or the owning controller's) move, not a failover.
                self._c_stale_epoch.inc()
            return None  # a key-level outcome: the caller's, verbatim
        hint = getattr(exc, "retry_after_s", None)
        self.mark_suspect(target.host_id, hint)
        if args is not None:
            key_id, data, m, b, deadline_ms, pri = args
            if pri is Priority.CRITICAL:
                ranked = self.map.placement(key_id, self.replicas)
                for nxt in ranked:
                    if nxt.host_id == target.host_id \
                            or self._routable_remaining(nxt.host_id) > 0 \
                            or self.health.state(nxt.host_id) == DOWN:
                        continue
                    pool = self._pools.get(nxt.host_id)
                    if pool is None:
                        continue  # left the ring mid-flight
                    try:
                        inner = pool.submit_bytes(
                            key_id, data, m=m, b=b,
                            deadline_ms=deadline_ms, priority=pri,
                            epoch=self.ring_epoch)
                    except BackendUnavailableError:
                        self.mark_suspect(nxt.host_id)
                        continue
                    self._c_failovers.inc()
                    self._count_forward(nxt.host_id)
                    return inner, nxt
        if hint is None:
            # Account every refusal: a bare transport death becomes
            # the ring's typed fail-fast refusal, hint attached (and
            # counted — this is a router-minted refusal, unlike the
            # pass-throughs above, which the shard already counted),
            # so a caller never sees an unhinted routing-tier failure.
            self._c_refused.inc()
            raise CircuitOpenError(
                f"shard {target.host_id!r} is suspect (transport "
                f"failure: {type(exc).__name__}: {exc}); failing fast "
                "until the cooldown elapses",
                retry_after_s=self.suspect_cooldown_s) from exc
        return None

    # -- submission ---------------------------------------------------

    def _count_forward(self, host_id: str) -> None:
        c = self._c_forwards.get(host_id)
        if c is None:  # a host added by set_ring after construction
            c = self.metrics.counter(labeled("router_forwards_total",
                                             shard=host_id))
            self._c_forwards[host_id] = c
        c.inc()

    def submit_bytes(self, key_id: str, data, b: int = 0,
                     deadline_ms: float | None = None,
                     priority=Priority.NORMAL):
        """Route one packed-bytes request (the edge front's entry;
        mirrors ``DcfService.submit_bytes``).  Returns a future whose
        failure modes are the shard's own typed taxonomy plus the
        routing tier's suspect refusal (``CircuitOpenError`` with
        ``retry_after_s``) — and, with ``co_eval="always"``, the mesh
        tier's ``MeshUnavailableError``.

        Dispatch (ISSUE 18): the co-evaluate policy decides first —
        a qualifying batch is scattered across the mesh group, with
        any mesh trouble degrading the WHOLE batch to route-mode
        (counted + warned) unless the caller forced the mesh."""
        pri = parse_priority(priority)
        view = memoryview(data).cast("B")
        if view.nbytes == 0 or view.nbytes % self.n_bytes:
            raise ShapeError(
                f"payload of {view.nbytes} bytes is not a positive "
                f"multiple of n_bytes={self.n_bytes}")
        m = view.nbytes // self.n_bytes
        if self._co_eval_applies(m):
            try:
                return self._submit_mesh(key_id, view, m, b,
                                         deadline_ms, pri)
            except MeshUnavailableError as e:
                if self.co_eval == "always":
                    raise
                self._mesh_degrade("mesh dispatch refused", e)
        return self._submit_route(key_id, view, m, b, deadline_ms, pri)

    def _co_eval_applies(self, m: int) -> bool:
        """Does the co-evaluate policy claim an ``m``-point batch?
        ``"always"`` claims everything (no group -> the mesh path
        refuses typed, which ``"always"`` surfaces); ``"auto"`` claims
        batches at or past the crossover when a group is formed."""
        if self.co_eval == "never":
            return False
        if self.co_eval == "always":
            return True
        return (self.mesh_group is not None
                and m >= self.co_eval_min_points)

    def _mesh_degrade(self, what: str, exc: BaseException) -> None:
        """Account one mesh -> route degradation: counted (the soak
        test's zero-lost-keys ledger) and warned (an operator watching
        stderr sees the pod quietly lose its co-evaluation tier)."""
        self._c_mesh_degraded.inc()
        warnings.warn(
            BackendFallbackWarning(f"mesh co-evaluate ({what})",
                                   "route-mode", exc),
            stacklevel=2)

    def _submit_mesh(self, key_id: str, view, m: int, b: int,
                     deadline_ms, pri):
        """Scatter one batch across the mesh group (co-evaluate
        dispatch).  Raises ``MeshUnavailableError`` — absorbed into a
        route-mode degradation by the dispatcher unless the caller
        forced the mesh — when no group is formed, the group's
        formation epoch trails the ring (membership moved; re-form
        with ``set_mesh``), a worker is unroutable (DOWN, suspect, or
        linkless), or a scatter send dies."""
        group = self.mesh_group
        try:
            fire("mesh.collective", m, 0 if group is None else len(group))
        except Exception as e:  # fallback-ok: the armed seam models a
            # collective that cannot form — same typed refusal as a
            # real dead mesh, so tests drive the degradation path
            # without killing a worker
            raise MeshUnavailableError(
                f"mesh collective failed ({type(e).__name__}: {e})",
                retry_after_s=self.health.interval_s) from e
        if group is None:
            raise MeshUnavailableError(
                "no mesh group formed (call set_mesh first)",
                retry_after_s=self.health.interval_s)
        if group.epoch != self.ring_epoch:
            raise MeshUnavailableError(
                f"mesh group formed at ring epoch {group.epoch} but "
                f"the ring is at {self.ring_epoch}; re-form with "
                "set_mesh",
                retry_after_s=self.health.interval_s)
        plan = group.plan(m)
        for sl in plan:
            if self.health.state(sl.host_id) == DOWN \
                    or self._routable_remaining(sl.host_id) > 0:
                raise MeshUnavailableError(
                    f"mesh worker {sl.host_id!r} is not routable",
                    retry_after_s=self.health.interval_s)
            if self._pools.get(sl.host_id) is None:
                raise MeshUnavailableError(
                    f"mesh worker {sl.host_id!r} has no link (left "
                    "the ring; re-form with set_mesh)",
                    retry_after_s=self.health.interval_s)
        parts = []
        for sl in plan:
            spec = self.map.get(sl.host_id)
            pool = self._pools.get(sl.host_id)
            if spec is None or pool is None:
                raise MeshUnavailableError(
                    f"mesh worker {sl.host_id!r} left the ring "
                    "mid-scatter; re-form with set_mesh",
                    retry_after_s=self.health.interval_s)
            # The scattered slice is a SUB-VIEW of the same received
            # frame buffer — the zero-copy relay contract holds across
            # the scatter (32-aligned boundaries keep the shard-side
            # pack word-aligned too).
            sub = view[sl.offset * self.n_bytes:
                       (sl.offset + sl.count) * self.n_bytes]
            try:
                inner = pool.submit_bytes(
                    key_id, sub, m=sl.count, b=b,
                    deadline_ms=deadline_ms, priority=pri,
                    epoch=self.ring_epoch)
            except BackendUnavailableError as e:
                # Scatter-time transport death: the worker is suspect
                # and the batch is NOT partially in flight from the
                # caller's perspective — the already-scattered slices
                # complete server-side and are discarded; route-mode
                # re-evaluates the whole batch.
                self.mark_suspect(sl.host_id)
                raise MeshUnavailableError(
                    f"mesh worker {sl.host_id!r} is unreachable "
                    f"({e})",
                    retry_after_s=self.health.interval_s) from e
            self._count_forward(sl.host_id)
            parts.append((inner, spec, sl))
        self._c_co_evals.inc()
        relay_args = None if self.co_eval == "always" else \
            (key_id, view, m, b, deadline_ms, pri)
        return _MeshFuture(self, parts, relay_args)

    def _submit_route(self, key_id: str, view, m: int, b: int,
                      deadline_ms, pri):
        """Route-mode dispatch: walk the key's ring placement (one
        host, one key) — the PR 13/14 semantics, unchanged."""
        ranked = self.map.placement(key_id, self.replicas)
        # PROMOTION (ISSUE 14): a host the prober holds DOWN leaves the
        # walk for EVERY class — its replica serves as acting owner (no
        # keys move; rendezvous already pinned the successor).  SUSPECT
        # keeps the PR 13 semantics below: CRITICAL fails over,
        # everyone else is refused typed until the state resolves.
        alive = [t for t in ranked
                 if self.health.state(t.host_id) != DOWN]
        if not alive:
            self._c_refused.inc()
            self._c_down_refused.inc()
            raise CircuitOpenError(
                f"every placed shard for {key_id!r} is DOWN "
                f"({[t.host_id for t in ranked]}); failing fast until "
                "a probe recovers one",
                retry_after_s=self.health.interval_s)
        alive_ids = {t.host_id for t in alive}
        args = (key_id, view, m, b, deadline_ms, pri)
        # Walk the placement: the first trusted holder gets the
        # forward.  Non-CRITICAL traffic only ever sees the acting
        # owner — replicas exist for continuity, not load spreading
        # (spreading would double-serve a key and hide owner sickness).
        candidates = alive if pri is Priority.CRITICAL else alive[:1]
        first_err: BaseException | None = None
        for i, target in enumerate(candidates):
            remaining = self._routable_remaining(target.host_id)
            if remaining > 0:
                if first_err is None:
                    first_err = CircuitOpenError(
                        f"shard {target.host_id!r} (acting owner of "
                        f"{key_id!r}) is suspect; failing fast",
                        retry_after_s=remaining)
                continue
            pool = self._pools.get(target.host_id)
            if pool is None:
                continue  # left the ring between placement and here
            try:
                inner = pool.submit_bytes(
                    key_id, view, m=m, b=b, deadline_ms=deadline_ms,
                    priority=pri, epoch=self.ring_epoch)
            except BackendUnavailableError as e:
                # Submit-time transport death: mark and keep walking
                # (CRITICAL) or refuse typed (everyone else).
                self.mark_suspect(target.host_id)
                if first_err is None:
                    first_err = CircuitOpenError(
                        f"shard {target.host_id!r} is unreachable "
                        f"({e}); failing fast until the cooldown "
                        "elapses",
                        retry_after_s=self.suspect_cooldown_s)
                first_err.__cause__ = e
                continue
            if target.host_id != ranked[0].host_id:
                if ranked[0].host_id not in alive_ids:
                    self._c_promoted.inc()  # owner DOWN: the replica
                    # is the acting owner (health-plane signal) ...
                else:
                    self._c_failovers.inc()  # ... vs the request-
                    # plane suspect walk — the metrics distinguish them
            self._count_forward(target.host_id)
            # Failover spending rule: the relay future may fail over
            # inline only if this forward went to the first acting
            # choice (a forward already down the walk has used the
            # ring once; the relay's own policy further restricts
            # inline failover to CRITICAL traffic).
            relay_args = args if i == 0 else None
            return _RelayFuture(self, inner, target, relay_args)
        self._c_refused.inc()
        raise first_err if first_err is not None else \
            CircuitOpenError(
                f"no shard available for {key_id!r}",
                retry_after_s=self.suspect_cooldown_s)

    def submit(self, key_id: str, xs, b: int = 0,
               deadline_ms: float | None = None,
               priority=Priority.NORMAL):
        """In-process convenience twin of ``DcfService.submit`` — the
        loadgen's router-target mode (ISSUE 13 satellite: ``open_loop``
        / ``closed_loop`` drive a router exactly like a service)."""
        xs = np.ascontiguousarray(np.asarray(xs, dtype=np.uint8))
        if xs.ndim != 2 or xs.shape[1] != self.n_bytes:
            raise ShapeError(
                f"xs must be [M, {self.n_bytes}], got {xs.shape}")
        if xs.shape[0] < 1:
            raise ShapeError("cannot submit an empty request")
        return self.submit_bytes(key_id, xs.data, b=b,
                                 deadline_ms=deadline_ms,
                                 priority=priority)

    def evaluate(self, key_id: str, xs, b: int = 0,
                 deadline_ms: float | None = None,
                 timeout: float | None = None,
                 priority=Priority.NORMAL) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(key_id, xs, b, deadline_ms,
                           priority).result(timeout)

    # -- registration (ISSUE 14: live-key replication) ----------------

    def register_frame(self, key_id: str, frame,
                       proto: bool = False) -> int:
        """Register one DCFK frame across the ring (the pod-door
        REGISTER verb — the fronting ``EdgeServer`` routes type-6
        frames here): the OWNER mints the generation, each replica
        applies it preserved (``serve.replicate.Replicator``).
        Returns the generation.  Live (non-durable): the durable twin
        is store provisioning via ``KeyStore.replicate_to``."""
        return self.replicator.register(key_id, frame,
                                        proto=bool(proto))

    def register_key(self, key_id: str, bundle) -> int:
        """In-process convenience twin of ``register_frame``: accepts
        a ``KeyBundle``, ``protocols.ProtocolBundle`` or
        ``protocols.DpfBundle`` and fans its frame out across the
        ring."""
        from dcf_tpu.protocols import ProtocolBundle

        proto = (isinstance(bundle, ProtocolBundle)
                 or getattr(bundle, "WIRE_PROTO", 0) != 0)
        return self.register_frame(key_id, bundle.to_bytes(),
                                   proto=proto)

    # -- mesh co-evaluation (ISSUE 18) --------------------------------

    def set_mesh(self, host_ids=None, *, epoch: int | None = None
                 ) -> MeshGroup:
        """Form (or re-form) the co-evaluation mesh group from ring
        members — default: every current member.  The group is fenced
        at the CURRENT ring epoch (or an explicit ``epoch``, for a
        controller forming the group inside the same membership
        commit): a later ``set_ring`` epoch bump invalidates it, and
        the next qualifying dispatch degrades to route-mode until the
        group is re-formed — a scatter can never land on an ejected
        host's successor ring by accident."""
        ids = self.map.host_ids() if host_ids is None else list(host_ids)
        for host_id in ids:
            if host_id not in self.map:
                # api-edge: mesh membership contract — a worker outside
                # the ring has no pool, no health target, no keys
                raise ValueError(
                    f"mesh worker {host_id!r} is not in the ring "
                    f"({self.map.host_ids()})")
        group = MeshGroup(
            ids, epoch=self.ring_epoch if epoch is None else int(epoch))
        self.mesh_group = group
        self._g_mesh_workers.set(len(group))
        return group

    def clear_mesh(self) -> None:
        """Dissolve the mesh group: subsequent dispatch is route-mode
        only (``co_eval="always"`` callers get ``MeshUnavailableError``
        typed).  In-flight co-evaluations keep the plan they started
        with (``MeshGroup`` is immutable)."""
        self.mesh_group = None
        self._g_mesh_workers.set(0)

    def register_mesh_frame(self, key_id: str, frame,
                            proto: bool = False) -> int:
        """Register one DCFK frame on EVERY mesh worker: co-evaluation
        scatters a batch pod-wide, so the key must be resident beyond
        its ring placement.  The ring walk goes first (the OWNER mints
        the generation — ``Replicator.register``, durable semantics
        unchanged), then each remaining mesh worker applies it
        preserved; a worker that cannot apply (dark, fenced) is
        skipped — the dispatch-time health check keeps a batch off a
        worker that missed the key's registration window, and
        anti-entropy converges it on recovery."""
        if self.mesh_group is None:
            raise MeshUnavailableError(
                "no mesh group formed (call set_mesh first)",
                retry_after_s=self.health.interval_s)
        gen = self.replicator.register(key_id, frame, proto=bool(proto))
        placed = self.map.placement_ids(key_id, self.replicas)
        for host_id in self.mesh_group.host_ids():
            if host_id in placed:
                continue  # the ring walk already registered it here
            pool = self._pools.get(host_id)
            if pool is None:
                continue  # left the ring mid-flight; set_mesh re-forms
            try:
                pool.register_frame(key_id, frame, generation=gen,
                                    proto=bool(proto),
                                    epoch=self.ring_epoch)
            except Exception:  # fallback-ok: a dark or fenced worker
                # must not fail an owner-acked registration — the
                # scatter-time health gate covers the window, and
                # anti-entropy heals the copy
                continue
        self._c_mesh_registered.inc()
        return int(gen)

    def register_mesh_key(self, key_id: str, bundle) -> int:
        """In-process convenience twin of ``register_mesh_frame``:
        accepts a ``KeyBundle``, ``protocols.ProtocolBundle`` or
        ``protocols.DpfBundle``."""
        from dcf_tpu.protocols import ProtocolBundle

        proto = (isinstance(bundle, ProtocolBundle)
                 or getattr(bundle, "WIRE_PROTO", 0) != 0)
        return self.register_mesh_frame(key_id, bundle.to_bytes(),
                                        proto=proto)

    # -- ring membership (ISSUE 14 satellite: bounded state) ----------

    def set_ring(self, shards, *, epoch: int | None = None,
                 retain=()) -> None:
        """Swap the shard ring atomically (``ShardMap`` or an iterable
        of ``ShardSpec``).  Removed hosts are FORGOTTEN — pool closed,
        suspect/backoff/health state dropped, labeled metric series
        removed (the ``BreakerBoard.forget`` cardinality discipline:
        host churn must not grow router state or its snapshot without
        limit).  Added hosts get fresh pools and health targets (a
        pool installed ahead of time by ``preconnect`` — the
        membership controller's pre-admission warm — is reused); a
        host whose ADDRESS changed (same id) is re-dialed.  In-flight
        requests keep the ranking they started with (the old map
        reference stays valid — ``ShardMap`` is immutable).

        ``epoch`` (ISSUE 15): the ring epoch this membership change is
        committed under — strictly monotonic; subsequent forwards,
        registrations and probes carry it, so shards structurally
        refuse any router still routing on the pre-change ring
        (``E_EPOCH``).  None leaves the epoch untouched (the PR 14
        operator-invoked swap semantics).  ``retain``: removed host
        ids whose pool/health state must be KEPT for now — a graceful
        drain's in-flight window; the controller calls
        ``forget_host`` after the drain grace elapses."""
        new = shards if isinstance(shards, ShardMap) \
            else ShardMap(shards)
        if epoch is not None and epoch <= self.ring_epoch:
            # api-edge: membership contract — the epoch IS the fence;
            # a reused or rolled-back value would let two conflicting
            # rings coexist as peers
            raise ValueError(
                f"ring epoch must be strictly monotonic: got {epoch} "
                f"at current epoch {self.ring_epoch}")
        retain = frozenset(retain)
        old = self.map
        old_ids = {s.host_id: s for s in old.hosts()}
        new_ids = {s.host_id: s for s in new.hosts()}
        # Added hosts get their pools BEFORE the map swaps: a submit
        # or registration placing onto the new ring must find the
        # link already dialed-able (the reverse order would open a
        # window where placement names a host with no pool).
        for host_id, spec in new_ids.items():
            if host_id not in old_ids:
                pool = self._pools.get(host_id)
                if pool is not None and (pool.host, pool.port) \
                        != spec.address:
                    # A retained (drain-grace) or preconnected pool
                    # wired to a DIFFERENT endpoint than the spec being
                    # admitted — reusing it would route every forward
                    # for this host to the old address.  Re-dial.
                    pool.close()
                    pool = None
                if pool is None:  # else: preconnect reuse
                    self._pools[host_id] = self._make_pool(spec)
                self._c_forwards[host_id] = self.metrics.counter(
                    labeled("router_forwards_total", shard=host_id))
                self._c_suspected[host_id] = self.metrics.counter(
                    labeled("router_suspected_total", shard=host_id))
                self.health.add_target(host_id,
                                       self._pools[host_id])
            elif old_ids[host_id].address != spec.address:
                # Same identity, new address: re-dial (placement is
                # keyed on host_id, so no keys move).
                stale = self._pools.pop(host_id, None)
                if stale is not None:
                    stale.close()
                self._pools[host_id] = self._make_pool(spec)
                self.health.add_target(host_id,
                                       self._pools[host_id])
        if epoch is not None:
            # Epoch first, map second: a forward racing the swap then
            # carries at worst (new epoch, old map) — served fine, the
            # placement is epoch-checked at membership commits, not
            # per key — never (old epoch, new map), which a shard that
            # already adopted the new epoch would refuse spuriously.
            self.ring_epoch = int(epoch)
            self._g_epoch.set(self.ring_epoch)
        self.map = new  # atomic reference swap
        for host_id in old_ids:
            if host_id not in new_ids and host_id not in retain:
                self._forget_host(host_id)

    def preconnect(self, spec: ShardSpec) -> EdgeClientPool:
        """Install (or return) a pool for a host NOT yet in the ring
        (ISSUE 15: the membership controller dials a joining host to
        warm it through the anti-entropy path BEFORE admission — no
        cold-miss storm on the first routed request).  Routing never
        consults pools for unmapped hosts, so the link is inert until
        ``set_ring`` admits it (which reuses this pool); an aborted
        join cleans up with ``forget_host``."""
        pool = self._pools.get(spec.host_id)
        if pool is not None and (pool.host, pool.port) != spec.address:
            # A leftover pool (a drain's retained link, or an earlier
            # preconnect) wired to a different endpoint: warming
            # through it would validate the WRONG process.  Re-dial.
            pool.close()
            pool = None
        if pool is None:
            pool = self._make_pool(spec)
            self._pools[spec.host_id] = pool
        return pool

    def forget_host(self, host_id: str) -> None:
        """Drop a host's pool/suspicion/health state and labeled
        series — the deferred half of a ``set_ring(..., retain=...)``
        drain (the pool must outlive the swap while in-flight relayed
        requests complete against it), and the cleanup for an aborted
        ``preconnect``.  Idempotent; refuses to forget a CURRENT ring
        member (that would leave placement naming a host with no
        link)."""
        if host_id in self.map:
            # api-edge: membership contract
            raise ValueError(
                f"host {host_id!r} is still in the ring; swap it out "
                "with set_ring before forgetting its state")
        self._forget_host(host_id)

    def _forget_host(self, host_id: str) -> None:
        """Drop EVERY piece of per-host router state for a host that
        left the ring (pinned by the cardinality test: churning hosts
        in and out leaves the suspect map, the pool table and the
        metrics snapshot exactly where they started)."""
        pool = self._pools.pop(host_id, None)
        if pool is not None:
            pool.close()
        self.health.remove_target(host_id)
        with self._lock:
            self._suspect_until.pop(host_id, None)
            now = self._clock()
            self._g_suspects.set(sum(
                1 for t in self._suspect_until.values() if t > now))
        self._c_forwards.pop(host_id, None)
        self._c_suspected.pop(host_id, None)
        for name in ("router_forwards_total", "router_suspected_total"):
            self.metrics.remove(labeled(name, shard=host_id))

    # -- lifecycle ----------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0,
              **edge_kwargs) -> "DcfRouter":
        """Front the router with its own DCFE ``EdgeServer`` (the pod
        door).  ``edge_kwargs`` pass through (``tls_cert``/``tls_key``
        terminate client TLS at the router; ``read_timeout_s`` etc.)."""
        if self.edge is None:
            self.edge = EdgeServer(self, host, port,
                                   **edge_kwargs).start()
        return self

    @property
    def address(self) -> tuple[str, int]:
        if self.edge is None:
            # api-edge: lifecycle contract, same spelling as EdgeServer
            raise ValueError("router edge not started (call start())")
        return self.edge.address

    def close(self) -> None:
        self.health.close()
        if self.edge is not None:
            self.edge.close()
            self.edge = None
        for pool in list(self._pools.values()):
            pool.close()

    def __enter__(self) -> "DcfRouter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- observability ------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """The ROUTER's own deterministic metrics (forwards per shard,
        failovers, suspect refusals, plus the fronting edge's series).
        The pod view — per-shard serve metrics summed — is
        ``serve.metrics.rollup_snapshots`` over the shards' own
        snapshots; the router cannot see inside its shards and does
        not pretend to."""
        return self.metrics.snapshot()

    def __repr__(self) -> str:
        return (f"DcfRouter(shards={self.map.host_ids()}, "
                f"replicas={self.replicas})")
