"""Active shard health probing for the pod routing tier (ISSUE 14).

PR 13's router learned about a dead shard only from request traffic: a
forwarded request died, the shard went suspect for a cooldown, and the
ring never HEALED — membership was static, a recovered shard waited for
lucky traffic to prove itself, and a SIGKILL'd shard stayed a
per-request cooldown loop forever.  This module is the control plane
that replaces per-request-only suspicion with observed state:

* the prober sends a lightweight DCFE **PING** frame to every shard
  through the router's existing ``EdgeClientPool``s — no second
  transport, no second health protocol — every ``interval_s``;
* per-shard state walks **UP -> SUSPECT -> DOWN -> UP** with fail-N /
  recover-M hysteresis (the PR 6 breaker vocabulary: ``fail_n``
  consecutive probe failures mirror ``failures_to_open``,
  ``recover_m`` consecutive successes mirror the half-open probe
  discipline — one blip is SUSPECT, not an outage; one lucky pong is
  not a recovery)::

                     1st probe failure
          UP ─────────────────────────► SUSPECT ──┐
          ▲  ◄──────────────────────────┘ │       │ fail_n consecutive
          │        1 probe success        │       │ failures (total)
          │                               ▼       ▼
          └───────────────────────────── DOWN ◄───┘
            recover_m consecutive successes
            AND the recovery gate passes (anti-entropy)

* every transition is a typed ``HealthEvent`` (drained via
  ``events()``, pushed via ``on_transition``) and a metrics write —
  ``router_health_state{shard=...}`` (0 up / 1 suspect / 2 down),
  ``router_probes_total`` / ``router_probe_failures_total{shard=...}``,
  ``router_health_transitions_total{to=...}`` — so dashboards and the
  chaos gates read the same facts the router routes on;
* **DOWN is promotion**: the router drops DOWN hosts from the
  placement walk for EVERY priority class, so each victim key's
  replica serves as owner (no keys move — rendezvous already pinned
  the successor).  SUSPECT keeps PR 13's semantics: CRITICAL fails
  over, everything else is refused typed with ``retry_after_s``;
* **recovery is gated**: the DOWN -> UP transition runs
  ``recover_gate(host_id)`` first (the router wires the anti-entropy
  pass here — ``serve.replicate``); a gate that fails or raises keeps
  the shard DOWN and resets the recovery count, because re-admitting
  a shard that could not converge its registrations would serve stale
  generations — the silent-wrong-answer partition bug.

Driving modes, mirroring ``DcfService``: ``start()`` spawns a daemon
thread probing every ``interval_s`` (production); ``pump()`` runs ONE
probe round inline — the deterministic mode tests drive with armed
fault seams and a fake clock (event timestamps come from the
injectable clock; the thread's wait is a plain ``Event.wait``, never
``time.*``).

Cardinality: ``remove_target`` (ring membership churn) forgets the
host's state AND its labeled metric series — the ``BreakerBoard.forget``
discipline applied to the health plane, so host churn cannot grow the
snapshot without limit.

Load sampling (ISSUE 16, ``serve.capacity``): a target that exposes
``ping_load`` (the router's ``EdgeClientPool`` does) is probed with it
instead of ``ping`` — the SAME round trip, now also carrying the
shard's ``edge.LoadSample`` back (queue points vs bound, brownout,
cumulative shed/refusal/pool-miss counters).  The freshest sample per
host is readable via ``loads()`` / ``load(host_id)``, the capacity
controller's input.  Gated exactly like the epoch kwarg: a scripted
test target without ``ping_load`` keeps its one-argument ``ping``
signature and simply yields no sample — liveness never depends on the
load surface.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["UP", "SUSPECT", "DOWN", "HEALTH_CODES", "HealthEvent",
           "HealthProber"]

UP = "up"
SUSPECT = "suspect"
DOWN = "down"

#: Gauge encoding, severity-sorted like the breaker's STATE_CODES.
HEALTH_CODES = {UP: 0, SUSPECT: 1, DOWN: 2}


@dataclass(frozen=True)
class HealthEvent:
    """One observed ring-state transition: ``host_id`` went
    ``frm -> to`` at injectable-clock time ``at``."""

    host_id: str
    frm: str
    to: str
    at: float


class _HostHealth:
    """Per-host hysteresis state (guarded by the prober's lock)."""

    __slots__ = ("state", "fails", "oks")

    def __init__(self):
        self.state = UP
        self.fails = 0  # consecutive probe failures
        self.oks = 0    # consecutive probe successes while DOWN


class HealthProber:
    """Active prober over ``{host_id: pingable}`` targets (anything
    with ``ping(timeout=)`` — the router hands its shard pools in).
    See the module docstring for the state machine and the driving
    modes.  Thread-safe: ``pump`` serializes probe rounds, the state
    lock makes reads consistent with the metrics that report them."""

    def __init__(self, targets: dict, *, interval_s: float = 0.25,
                 timeout_s: float | None = None, fail_n: int = 3,
                 recover_m: int = 2, clock=monotonic,
                 metrics: Metrics | None = None, recover_gate=None,
                 on_transition=None, max_events: int = 256,
                 epoch_source=None):
        if interval_s <= 0:
            # api-edge: prober config contract
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        if fail_n < 1 or recover_m < 1:
            # api-edge: prober config contract — 0 would transition on
            # nothing, i.e. flap on every probe
            raise ValueError(
                f"fail_n/recover_m must be >= 1, got "
                f"{fail_n}/{recover_m}")
        self.interval_s = float(interval_s)
        # Default probe budget: generous relative to the interval — a
        # ping slower than the cadence on a loaded host is congestion,
        # not death, and a too-tight budget turns CPU contention into
        # spurious DOWN verdicts (a dead/cut target still fails FAST:
        # refused dials and resets do not wait the budget out).
        self.timeout_s = (float(timeout_s) if timeout_s is not None
                          else max(self.interval_s, 1.0))
        self.fail_n = int(fail_n)
        self.recover_m = int(recover_m)
        self._clock = clock
        self._metrics = metrics if metrics is not None else Metrics()
        self._recover_gate = recover_gate
        self._on_transition = on_transition
        # Epoch dissemination (ISSUE 15): when set (a zero-arg callable
        # returning the router's current ring epoch), every probe
        # carries it — shards adopt a committed membership epoch within
        # about one probe interval, and a STALE prober's pings are
        # refused E_EPOCH (one more probe failure: the hysteresis walks
        # the stale router's view DOWN, which is exactly the structural
        # refusal the fence promises).  None = unfenced pings, and the
        # target's ``ping`` is called WITHOUT the epoch kwarg (scripted
        # test targets keep their one-argument signature).
        self._epoch_source = epoch_source
        self._max_events = int(max_events)
        self._lock = threading.Lock()
        self._pump_lock = threading.Lock()  # one probe round at a time
        # guarded-by: _lock
        self._targets = dict(targets)
        # guarded-by: _lock
        self._hosts = {hid: _HostHealth() for hid in self._targets}
        # Freshest per-host LoadSample off the probe round trip
        # (ISSUE 16): None = probed but no load surface; absent =
        # never successfully probed (or removed).
        # guarded-by: _lock
        self._loads: dict = {}
        # guarded-by: _lock
        self._events: list[HealthEvent] = []
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        m = self._metrics
        self._c_transitions = m.counter(
            "router_health_transitions_total")
        self._c_gate_failures = m.counter(
            "router_recover_gate_failures_total")
        self._g_down = m.gauge("router_down_shards")
        for hid in self._targets:
            self._init_series(hid)

    def _init_series(self, host_id: str) -> None:
        self._metrics.gauge(labeled(
            "router_health_state", shard=host_id)).set(0)
        self._metrics.counter(labeled(
            "router_probes_total", shard=host_id))
        self._metrics.counter(labeled(
            "router_probe_failures_total", shard=host_id))

    # -- state reads --------------------------------------------------

    def state(self, host_id: str) -> str:
        with self._lock:
            h = self._hosts.get(host_id)
            return h.state if h is not None else UP

    def states(self) -> dict:
        with self._lock:
            return {hid: h.state for hid, h in self._hosts.items()}

    def events(self) -> list:
        """Drain the typed transition events observed so far (bounded:
        oldest dropped past ``max_events`` — the stream is a debugging
        aid; the state machine and metrics are the durable record)."""
        with self._lock:
            out, self._events = self._events, []
            return out

    def loads(self) -> dict:
        """Freshest ``{host_id: LoadSample | None}`` sampled off the
        probe round trips (ISSUE 16).  None = the host answered but
        has no load surface; a host that never answered a load probe
        is absent.  A snapshot copy — safe to iterate while probing."""
        with self._lock:
            return dict(self._loads)

    def load(self, host_id: str):
        """The freshest ``LoadSample`` for one host (None if absent
        or load-free)."""
        with self._lock:
            return self._loads.get(host_id)

    # -- membership (ISSUE 14 satellite: bounded cardinality) ---------

    def add_target(self, host_id: str, target) -> None:
        with self._lock:
            self._targets[host_id] = target
            if host_id not in self._hosts:
                self._hosts[host_id] = _HostHealth()
        self._init_series(host_id)

    def remove_target(self, host_id: str) -> None:
        """Forget a host that left the ring: state AND its labeled
        series (the ``BreakerBoard.forget`` cardinality discipline —
        host churn must not grow probe state or the snapshot without
        limit)."""
        with self._lock:
            self._targets.pop(host_id, None)
            self._hosts.pop(host_id, None)
            self._loads.pop(host_id, None)
        for name in ("router_health_state", "router_probes_total",
                     "router_probe_failures_total"):
            self._metrics.remove(labeled(name, shard=host_id))
        self._sync_down_gauge()

    # -- probing ------------------------------------------------------

    def pump(self) -> dict:
        """One probe round inline (the deterministic driving mode):
        ping every target, feed the outcomes through the hysteresis,
        return the post-round ``{host_id: state}``."""
        with self._pump_lock:
            with self._lock:
                targets = list(self._targets.items())
            for host_id, target in targets:
                self._metrics.counter(labeled(
                    "router_probes_total", shard=host_id)).inc()
                sampler = getattr(target, "ping_load", None)
                kwargs = {"timeout": self.timeout_s}
                if self._epoch_source is not None:
                    kwargs["epoch"] = int(self._epoch_source())
                try:
                    if callable(sampler):
                        # One round trip, two facts: liveness AND the
                        # shard's demand signals (ISSUE 16) — never a
                        # second probe protocol.
                        _, sample = sampler(**kwargs)
                        with self._lock:
                            if host_id in self._hosts:
                                self._loads[host_id] = sample
                        ok = True
                    elif self._epoch_source is not None:
                        ok = bool(target.ping(**kwargs))
                    else:
                        # Scripted test targets keep their one-argument
                        # signature (no epoch kwarg, no load surface).
                        ok = bool(target.ping(timeout=self.timeout_s))
                except Exception:  # fallback-ok: ANY probe failure
                    # (transport death, dark-target backoff, timeout)
                    # is one observation for the hysteresis — the
                    # prober must outlive every probe outcome
                    ok = False
                if not ok:
                    self._metrics.counter(labeled(
                        "router_probe_failures_total",
                        shard=host_id)).inc()
                self.observe(host_id, ok)
            return self.states()

    def observe(self, host_id: str, ok: bool) -> None:
        """Feed one probe outcome through the hysteresis (public so
        tests — and a router that learned something out-of-band — can
        drive the state machine without a socket)."""
        gate_host = None
        with self._lock:
            h = self._hosts.get(host_id)
            if h is None:
                return  # removed mid-round: nothing to resurrect
            before = h.state
            if ok:
                if h.state == UP:
                    h.fails = 0
                elif h.state == SUSPECT:
                    # One good probe clears a blip (the breaker's
                    # half-open-success analog at the suspicion stage).
                    h.state = UP
                    h.fails = 0
                else:  # DOWN
                    h.fails = 0  # the consecutive-failure run is
                    # broken; _try_recover's post-gate check reads
                    # fails > 0 as "new failure evidence mid-gate"
                    h.oks += 1
                    if h.oks >= self.recover_m:
                        h.oks = 0
                        gate_host = host_id  # gate OUTSIDE the lock
            else:
                h.oks = 0
                h.fails += 1
                if h.state == UP:
                    h.state = SUSPECT
                elif h.state == SUSPECT and h.fails >= self.fail_n:
                    h.state = DOWN
            after = h.state
        if after != before:
            self._transition(host_id, before, after)
        if gate_host is not None:
            self._try_recover(gate_host)

    def _try_recover(self, host_id: str) -> None:
        """recover_m consecutive successes observed on a DOWN host:
        run the recovery gate (anti-entropy) and only then re-admit.
        Runs OUTSIDE the state lock — the gate does wire round trips —
        so a concurrent failed probe can race it; the post-gate check
        re-admits only a host that is still DOWN with no new failure
        evidence (``oks`` was reset, so a race costs at most one more
        recover_m window, never a wrong UP)."""
        if self._recover_gate is not None:
            try:
                gate_ok = self._recover_gate(host_id)
            except Exception:  # fallback-ok: a failing gate (a peer
                # died mid-exchange) keeps the shard DOWN — counted,
                # retried on the next recover_m window
                gate_ok = False
            if not gate_ok:
                self._c_gate_failures.inc()
                return
        with self._lock:
            h = self._hosts.get(host_id)
            if h is None or h.state != DOWN or h.fails > 0:
                return
            h.state = UP
            h.fails = 0
            h.oks = 0
        self._transition(host_id, DOWN, UP)

    def _transition(self, host_id: str, frm: str, to: str) -> None:
        ev = HealthEvent(host_id, frm, to, self._clock())
        with self._lock:
            self._events.append(ev)
            del self._events[:-self._max_events]
        self._c_transitions.inc()
        self._metrics.counter(labeled(
            "router_health_transitions_total", to=to)).inc()
        self._metrics.gauge(labeled(
            "router_health_state",
            shard=host_id)).set(HEALTH_CODES[to])
        self._sync_down_gauge()
        if self._on_transition is not None:
            self._on_transition(ev)

    def _sync_down_gauge(self) -> None:
        with self._lock:
            self._g_down.set(sum(
                1 for h in self._hosts.values() if h.state == DOWN))

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "HealthProber":
        """Spawn the probe worker (idempotent)."""
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._worker_loop, name="dcf-health-probe",
                daemon=True)
            self._worker.start()
        return self

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            self.pump()
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        worker = self._worker
        if worker is not None and worker.is_alive() \
                and worker is not threading.current_thread():
            worker.join(5.0)
        self._worker = None

    def __repr__(self) -> str:
        # dcflint: disable=guarded-by diagnostic snapshot: sorted()
        # copies under the GIL, and a repr racing add/remove_target may
        # legitimately show either side of the change
        return (f"HealthProber(hosts={sorted(self._targets)}, "
                f"interval_s={self.interval_s}, fail_n={self.fail_n}, "
                f"recover_m={self.recover_m})")
