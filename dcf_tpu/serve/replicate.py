"""Live-key replication + partition-tolerant anti-entropy (ISSUE 14).

PR 13 made DURABLE keys survive a shard death: ``KeyStore.replicate_to``
copies the frame into the replica's store at provisioning time, and the
replica restores it warm.  This module is the LIVE (non-durable) twin
plus the repair loop that makes partitions heal instead of fester:

* **Registration fan-out** (``Replicator.register``): a key registered
  through the pod tier is forwarded as a DCFE REGISTER frame — the raw
  DCFK bundle bytes, by reference — first to its ring OWNER (which
  MINTS the generation) and then to each replica with the owner's
  generation preserved (the wire format already round-trips
  generations).  A replica forward that fails is counted, never fatal:
  the registration is acked once the owner holds it, and anti-entropy
  converges the replica when it next heals.  ``KeyStore.replicate_to``
  stays the durable twin — this path deliberately writes no store.

* **The monotonic-generation fence** (``apply_frame`` /
  ``KeyRegistry.register_at``): a forwarded frame whose generation is
  at or below the local entry's dies typed ``StaleStateError``
  (``E_STALE`` on the wire), counted (``serve_replica_fenced_total``).
  The fence is what makes an old partition side structurally unable to
  roll a key back: generations are minted by exactly one owner per
  key, every apply preserves them, and the only way to supersede a
  registration anywhere in the ring is a strictly newer one.

* **Anti-entropy** (``Replicator.anti_entropy``): a restarting or
  partition-healed shard exchanges a ``{key_id: generation}`` digest
  with its peers (DIGEST/SYNC frames — generations travel first, key
  material only for the strictly-newer set) and pulls exactly the
  frames it is behind on, filtered to the keys the ring places on it.
  The pod ROUTER orchestrates the exchange through its existing shard
  pools as the health prober's recovery gate — a DOWN shard is
  re-admitted only after the pass completes, which also restores the
  ordering that keeps generations safe across an owner restart: the
  recovered owner's registry floors its counter on the pulled
  generations BEFORE any new registration can mint.

Secret hygiene: the frame bytes handled here are key material (the
dcflint secret-hygiene name set knows ``frame``/``frame_bytes``); this
module logs names, generations and counts only.

Clocking: none — replication is driven by registrations and the health
prober's transitions; timeouts belong to the edge clients.
"""

from __future__ import annotations

from dcf_tpu.errors import (
    BackendUnavailableError,
    ShapeError,
    StaleStateError,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.serve.metrics import Metrics

__all__ = ["Replicator", "decode_key_frame", "apply_frame",
           "sync_frames"]


def decode_key_frame(frame, proto: bool):
    """One DCFK frame off the wire -> the registrable object (the
    existing codecs verbatim — ``KeyBundle`` v2, or the v3 proto
    dispatcher for protocol frames (``ProtocolBundle`` for MIC,
    ``DpfBundle`` for DPF); corruption dies typed ``KeyFormatError``
    inside them)."""
    frame_bytes = bytes(frame)
    if proto:
        from dcf_tpu.protocols import decode_proto_frame

        return decode_proto_frame(frame_bytes)
    return KeyBundle.from_bytes(frame_bytes)


def _unwrap(obj):
    """``(registrable bundle, protocol-or-None)`` for any bundle kind.
    A ``DpfBundle`` is self-contained (its frame IS the key material,
    no combine-mask wrapper), so it registers directly with no
    protocol record — the registry only needs the two-party ``s0s``
    shape and the geometry props, which it shares with ``KeyBundle``."""
    from dcf_tpu.protocols import ProtocolBundle

    if isinstance(obj, ProtocolBundle):
        return obj.keys, obj
    return obj, None


def _check_geometry(key_id: str, bundle: KeyBundle, lam: int,
                    n_bytes: int) -> None:
    if bundle.lam != lam:
        raise ShapeError(
            f"replica frame for {key_id!r} carries lam {bundle.lam} "
            f"!= service lam {lam}")
    if bundle.n_bits != 8 * n_bytes:
        raise ShapeError(
            f"replica frame for {key_id!r} carries domain "
            f"{bundle.n_bits} bits != service domain {8 * n_bytes} "
            "bits")


def apply_frame(registry, key_id: str, frame, generation: int,
                proto: bool, *, lam: int, n_bytes: int,
                metrics: Metrics) -> int:
    """Apply one forwarded frame under the owner's generation (the
    fenced replica/anti-entropy spelling).  Returns the generation; a
    rollback attempt raises ``StaleStateError`` and bumps
    ``serve_replica_fenced_total`` — fenced typed, counted, never
    served."""
    obj = decode_key_frame(frame, proto)
    bundle, protocol = _unwrap(obj)
    _check_geometry(key_id, bundle, lam, n_bytes)
    try:
        gen = registry.register_at(key_id, bundle, generation,
                                   protocol=protocol)
    except StaleStateError:
        metrics.counter("serve_replica_fenced_total").inc()
        raise
    metrics.counter("serve_replica_applied_total").inc()
    return gen


#: Per-SYNC-response payload cap: well under the edge clients' default
#: ``max_frame_bytes`` (256 MiB), so a heal with an arbitrarily large
#: backlog streams in bounded chunks instead of one response the
#: puller's frame bound would reject — which would deadlock recovery
#: exactly when the backlog is largest.  The puller iterates: each
#: applied chunk advances its digest, so the next request returns the
#: NEXT chunk, until nothing newer remains.
SYNC_MAX_BYTES = 32 << 20

#: Digest sentinel meaning "never send this key" (u64 max on the
#: wire): the anti-entropy puller marks keys the ring does NOT place
#: on its target, so unplaced key material never moves — filtering
#: happens at the SENDER, not after the bytes crossed.
DIGEST_SUPPRESS = (1 << 64) - 1


def sync_frames(registry, digest: dict,
                max_bytes: int = SYNC_MAX_BYTES) -> list:
    """The anti-entropy serve half: keys whose generation is STRICTLY
    newer than the caller's digest records (missing = 0), as
    ``(key_id, generation, proto, frame_bytes)`` entries in sorted key
    order, capped at ~``max_bytes`` of frame payload per response
    (at least one entry always ships, so a single oversized frame
    still moves).  Strictness is load-bearing: an equal generation
    means the caller already holds these bytes, and "newer or equal"
    would turn every heal into a full-ring copy."""
    entries = []
    total = 0
    for key_id in sorted(registry.digest()):
        try:
            bundle, protocol, generation = registry.snapshot(key_id)
        except ValueError:
            continue  # unregistered between digest and snapshot
        if generation <= int(digest.get(key_id, 0)):
            continue
        frame_bytes = (protocol.to_bytes() if protocol is not None
                       else bundle.to_bytes())
        is_proto = (protocol is not None
                    or getattr(bundle, "WIRE_PROTO", 0) != 0)
        if entries and total + len(frame_bytes) > max_bytes:
            break  # this response is full; the puller comes back
        entries.append((key_id, generation, is_proto, frame_bytes))
        total += len(frame_bytes)
    return entries


class Replicator:
    """Router-side registration fan-out + anti-entropy orchestration
    (see the module docstring).

    ``pools``: the router's live ``{host_id: EdgeClientPool}`` mapping
    (shared, not copied — ring membership changes show up here).
    ``ring``: zero-arg callable returning the current ``ShardMap``
    (the router swaps its map atomically; the replicator must read the
    same reference).  ``replicas``: ranking successors that hold each
    key (the router's own knob).
    """

    def __init__(self, pools: dict, ring, *, replicas: int = 1,
                 metrics: Metrics | None = None,
                 timeout_s: float = 30.0, epoch_source=None):
        self._pools = pools
        self._ring = ring
        self._replicas = int(replicas)
        self._timeout_s = float(timeout_s)
        # ISSUE 15: zero-arg callable returning the router's current
        # ring epoch — forwarded REGISTER frames then carry it, so a
        # stale router's registrations are fenced E_EPOCH at the shard
        # instead of landing on a placement the pod has moved past.
        # None = unfenced (epoch 0 on the wire).
        self._epoch_source = epoch_source
        m = metrics if metrics is not None else Metrics()
        self._c_registered = m.counter("router_registered_total")
        self._c_replicated = m.counter("router_replicated_total")
        self._c_repl_failures = m.counter(
            "router_replicate_failures_total")
        self._c_fenced = m.counter("router_replica_fenced_total")
        self._c_ae_runs = m.counter("router_anti_entropy_runs_total")
        self._c_ae_frames = m.counter(
            "router_anti_entropy_frames_total")
        self._c_ae_fenced = m.counter(
            "router_anti_entropy_fenced_total")

    def register(self, key_id: str, frame, *, proto: bool = False,
                 timeout: float | None = None) -> int:
        """Fan one registration out across the ring: the OWNER mints
        the generation (a failed owner forward fails the registration
        — there is no ack without an owner); each replica applies with
        that generation preserved.  A replica forward that dies
        (transport, fence) is counted and skipped — anti-entropy
        converges it on the replica's next recovery."""
        timeout = self._timeout_s if timeout is None else timeout
        epoch = (int(self._epoch_source())
                 if self._epoch_source is not None else 0)
        placed = self._ring().placement(key_id, self._replicas)
        owner = placed[0]
        # .get, never [] — a registration racing a ``set_ring``
        # membership swap must fail TYPED (owner) or heal later
        # (replica), not crash the caller with a bare KeyError (the
        # router's own submit paths guard the identical race).
        owner_pool = self._pools.get(owner.host_id)
        if owner_pool is None:
            raise BackendUnavailableError(
                f"owner shard {owner.host_id!r} for {key_id!r} has no "
                "link (ring membership changed mid-registration)")
        gen = owner_pool.register_frame(
            key_id, frame, generation=0, proto=proto, timeout=timeout,
            epoch=epoch)
        self._c_registered.inc()
        for rep in placed[1:]:
            pool = self._pools.get(rep.host_id)
            if pool is None:
                self._c_repl_failures.inc()  # left the ring mid-
                # flight: the new ring's anti-entropy owns convergence
                continue
            try:
                pool.register_frame(
                    key_id, frame, generation=gen, proto=proto,
                    timeout=timeout, epoch=epoch)
                self._c_replicated.inc()
            except StaleStateError:
                # The replica already holds a NEWER generation — the
                # fence held against a racing re-registration; the
                # newer key wins by design.
                self._c_fenced.inc()
            except Exception:  # fallback-ok: replica darkness must not
                # fail an owner-acked registration — counted, healed by
                # the anti-entropy pass on recovery
                self._c_repl_failures.inc()
        return int(gen)

    def anti_entropy(self, target_host_id: str, *, peer_ok=None,
                     timeout: float | None = None, ring=None,
                     peers=None) -> int:
        """Converge ``target_host_id`` with its ring peers: pull the
        target's digest, ask each reachable peer for strictly-newer
        frames, and forward to the target exactly those the ring
        places on it.  Returns the number of frames applied.

        A PEER that fails the exchange raises — the caller (the health
        prober's recovery gate) must keep the target DOWN rather than
        re-admit a shard that could not see part of the ring: serving
        a stale generation would be the silent-wrong-answer partition
        bug this pass exists to close.  ``peer_ok(host_id)`` excludes
        peers the caller already knows are down (their absence is
        accounted by THEIR health state, not this pass).

        ``ring`` / ``peers`` (ISSUE 15, the membership controller's
        reuse): ``ring`` overrides the live map — the PROSPECTIVE ring
        for a graceful join's pre-admission warm, the POST-eject/drain
        ring for a migration — and decides placement filtering;
        ``peers`` overrides the consulted source host ids (a draining
        host has left the new ring but is the primary source of its
        own keys; a joining host is not in the old ring at all).
        Defaults reproduce the PR 14 recovery-gate behavior exactly:
        the live ring, every OTHER member as a peer."""
        timeout = self._timeout_s if timeout is None else timeout
        ring = self._ring() if ring is None else ring
        target_pool = self._pools.get(target_host_id)
        if target_pool is None:
            raise BackendUnavailableError(
                f"shard {target_host_id!r} has no link (left the "
                "ring); nothing to converge")
        digest = target_pool.pull_digest(timeout)
        self._c_ae_runs.inc()
        pulled = 0
        peer_ids = (list(peers) if peers is not None
                    else ring.host_ids())
        for peer_id in peer_ids:
            if peer_id == target_host_id:
                continue
            if peer_ok is not None and not peer_ok(peer_id):
                continue
            peer_pool = self._pools.get(peer_id)
            if peer_pool is None:
                continue  # left the ring mid-pass: its keys moved
            # Sender-side placement filtering: pull the peer's digest
            # (names + generations, NO key material) and SUPPRESS
            # every key the ring does not place on the target — the
            # peer then never serializes those frames, so unplaced
            # key material never crosses the wire only to be dropped.
            peer_digest = peer_pool.pull_digest(timeout)
            want = dict(digest)
            for key_id in peer_digest:
                if target_host_id not in ring.placement_ids(
                        key_id, self._replicas):
                    want[key_id] = DIGEST_SUPPRESS
            # Iterate: each SYNC response is CAPPED (SYNC_MAX_BYTES);
            # applying a chunk advances ``want``, so the next request
            # returns the next chunk — an arbitrarily large backlog
            # streams in bounded frames instead of one response the
            # puller's frame bound would reject (which would wedge
            # recovery exactly when the backlog is largest).
            while True:
                entries = peer_pool.sync_newer(want, timeout)
                if not entries:
                    break
                for key_id, gen, proto, frame in entries:
                    if gen <= int(want.get(key_id, 0)):
                        continue  # belt: the server already filtered
                    try:
                        target_pool.register_frame(
                            key_id, frame, generation=gen,
                            proto=proto, timeout=timeout)
                    except StaleStateError:
                        # The target pulled this key from an earlier
                        # peer at a newer generation, or re-registered
                        # it since the digest — the fence held;
                        # convergence is per-key monotone either way.
                        self._c_ae_fenced.inc()
                    else:
                        digest[key_id] = gen
                        pulled += 1
                        self._c_ae_frames.inc()
                    # Advance past this key EITHER way: a fenced key
                    # is one the target already holds at >= gen, and
                    # not advancing would make the peer resend it in
                    # every chunk forever (a livelock, not a heal).
                    want[key_id] = max(int(want.get(key_id, 0)), gen)
        return pulled

    def __repr__(self) -> str:
        return (f"Replicator(hosts={sorted(self._pools)}, "
                f"replicas={self._replicas})")
