"""Per-(key_id, backend-family) circuit breakers for the serving layer.

PR 4's retry discipline assumed one-shot faults: a batch fails, the
retry runs the shared invalidation path, the re-staged backend serves.
Production backends do not fail once — they fail for a *window* (a
wedged driver, a recompiling kernel, a remote core restarting), and
during that window every retried batch burns its full retry budget and
its callers' deadline headroom before failing anyway.  The breaker is
the memory that turns "this batch failed" into "this backend family is
failing for this key": after ``failures_to_open`` consecutive recorded
failures the breaker OPENS and subsequent batches fail fast with
``errors.CircuitOpenError`` (or, for an auto facade, the final-retry
``reset_backend_health`` demotion has already moved the family down the
pallas -> bitsliced -> jax -> numpy chain — a new family is a new
breaker, born closed).

State machine (the classic three-state breaker)::

                 failures >= threshold
      CLOSED ───────────────────────────► OPEN ◄──┐
        ▲                                  │      │ probe fails
        │ probe succeeds                   │ cooldown elapses
        │                                  ▼      │
        └────────────────────────────── HALF_OPEN ┘

* CLOSED: every batch dispatches; a success resets the consecutive-
  failure count.
* OPEN: non-CRITICAL batches fail fast (``CircuitOpenError``) without
  touching the backend; CRITICAL-priority batches bypass and dispatch
  (their outcomes are recorded but do not transition an open breaker —
  a bypass success is not a sanctioned probe, and treating it as one
  would let a lucky critical flip the breaker mid-cooldown, i.e.
  thrash).  After ``cooldown_s`` on the injectable clock the first
  ``allow`` becomes the half-open probe.
* HALF_OPEN: exactly one probe is in flight; other non-CRITICAL batches
  keep failing fast (a half-open flood would hammer the recovering
  backend).  Probe success closes the breaker; any recorded failure
  re-opens it and restarts the cooldown.

Keying: breakers live per (key_id, backend-family) — the failure domain
is the pairing, not the key (a key that died on pallas is healthy on
the demoted bitsliced path) and not the family (one key's poisoned
frontier must not open every other key's breaker).  The board survives
registry hot-swaps and LRU residency evictions by construction: breaker
state is *history about a serving pairing*, and a re-registered bundle
re-staged onto the same dying backend is still on a dying backend.
``forget(key_id)`` (unregistration) is the one deliberate reset.

Clocking: all cooldown math uses the injectable clock
(``utils.benchtime.monotonic`` by default), never ``time.*`` — the
dcflint determinism pass holds this module to that, and the chaos tests
replay whole open/half-open/close walks on a fake clock.

Metrics: per-pairing ``serve_breaker_state{backend=...,key=...}`` gauge
(0 closed / 1 half-open / 2 open), aggregate ``serve_breakers_open``
gauge, and ``serve_breaker_transitions_total`` (plus a ``{to=...}``
labeled series per target state) — the counters the chaos harness
asserts its scripted scenarios against.
"""

from __future__ import annotations

import threading

from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["CLOSED", "HALF_OPEN", "OPEN", "STATE_CODES",
           "CircuitBreaker", "BreakerBoard"]

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

#: Gauge encoding: sorted by severity so dashboards can max() over keys.
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """One (key_id, backend-family) breaker; see the module docstring.

    Not self-locking: the owning ``BreakerBoard`` serializes every call
    (state transitions must be atomic with the metrics that report
    them).  Usable standalone in single-threaded tests.
    """

    __slots__ = ("failures_to_open", "cooldown_s", "state", "failures",
                 "opened_at", "probe_inflight")

    def __init__(self, failures_to_open: int, cooldown_s: float):
        if failures_to_open < 1:
            # api-edge: constructor bound contract (0 disables breakers
            # at the ServeConfig level, not per instance)
            raise ValueError(
                f"failures_to_open must be >= 1, got {failures_to_open}")
        if cooldown_s < 0:
            # api-edge: constructor bound contract
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.failures_to_open = int(failures_to_open)
        self.cooldown_s = float(cooldown_s)
        self.state = CLOSED
        self.failures = 0  # consecutive, reset by any success when closed
        self.opened_at = 0.0
        self.probe_inflight = False

    def allow(self, now: float, critical: bool = False) -> bool:
        """May a new batch dispatch?  OPEN -> HALF_OPEN happens here
        (the allowed caller becomes the probe) once the cooldown has
        elapsed on the injected clock."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if now - self.opened_at >= self.cooldown_s:
                self.state = HALF_OPEN
                self.probe_inflight = True
                return True
            return critical  # CRITICAL bypasses the open window
        # HALF_OPEN: one probe at a time; criticals ride along.
        if critical:
            return True
        if not self.probe_inflight:
            self.probe_inflight = True
            return True
        return False

    def record_success(self) -> None:
        # No clock: success timing never matters to the state machine
        # (only record_failure stamps opened_at).
        if self.state == CLOSED:
            self.failures = 0
        elif self.state == HALF_OPEN:
            self.state = CLOSED
            self.failures = 0
            self.probe_inflight = False
        # OPEN: a CRITICAL bypass that got lucky is not a probe — the
        # breaker waits for the cooldown + sanctioned probe (no thrash).

    def record_failure(self, now: float) -> None:
        if self.state == CLOSED:
            self.failures += 1
            if self.failures >= self.failures_to_open:
                self.state = OPEN
                self.opened_at = now
        elif self.state == HALF_OPEN:
            self.state = OPEN
            self.opened_at = now  # cooldown restarts after a failed probe
            self.probe_inflight = False
        # OPEN: a CRITICAL bypass failing changes nothing — restarting
        # the cooldown on bypass traffic would keep a busy breaker open
        # forever (the starvation flavor of thrash).

    def abort_probe(self) -> None:
        """The caller that ``allow`` sanctioned as the half-open probe
        died without a batch outcome (e.g. the key was unregistered
        between the gate and the dispatch).  Release the probe slot so
        the NEXT allow can probe — without this, a vanished prober would
        wedge the breaker half-open forever (criticals only)."""
        if self.state == HALF_OPEN:
            self.probe_inflight = False


class BreakerBoard:
    """Registry of per-(key_id, backend-family) breakers + metrics.

    Thread-safe; one lock serializes state transitions with the gauges
    and counters that report them, so a metrics snapshot can never show
    an open count that disagrees with the per-pairing state gauges.
    """

    def __init__(self, *, failures_to_open: int = 3,
                 cooldown_s: float = 5.0,
                 metrics: Metrics | None = None, clock=monotonic):
        self.failures_to_open = int(failures_to_open)
        self.cooldown_s = float(cooldown_s)
        self._metrics = metrics if metrics is not None else Metrics()
        self._clock = clock
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._breakers: dict[tuple[str, str], CircuitBreaker] = {}
        # guarded-by: _lock
        self._open = 0
        self._g_open = self._metrics.gauge("serve_breakers_open")
        self._c_transitions = self._metrics.counter(
            "serve_breaker_transitions_total")

    # -- internals (call under self._lock) ----------------------------------

    # holds-lock: _lock
    def _get(self, key_id: str, family: str) -> CircuitBreaker:
        br = self._breakers.get((key_id, family))
        if br is None:
            br = CircuitBreaker(self.failures_to_open, self.cooldown_s)
            self._breakers[(key_id, family)] = br
        return br

    # holds-lock: _lock
    def _sync(self, key_id: str, family: str, br: CircuitBreaker,
              before: str) -> None:
        if br.state == before:
            return
        self._c_transitions.inc()
        self._metrics.counter(labeled(
            "serve_breaker_transitions_total", to=br.state)).inc()
        self._metrics.gauge(labeled(
            "serve_breaker_state", backend=family,
            key=key_id)).set(STATE_CODES[br.state])
        self._open += (br.state == OPEN) - (before == OPEN)
        self._g_open.set(self._open)

    # -- the serving-layer surface ------------------------------------------

    def allow(self, key_id: str, family: str,
              critical: bool = False) -> bool:
        with self._lock:
            br = self._get(key_id, family)
            before = br.state
            ok = br.allow(self._clock(), critical)
            self._sync(key_id, family, br, before)
            return ok

    def record_success(self, key_id: str, family: str) -> None:
        with self._lock:
            br = self._breakers.get((key_id, family))
            if br is None:
                # Every dispatch passes the allow() gate first (which
                # creates the entry), so a missing pairing here means
                # forget() raced an in-flight batch: the key was
                # unregistered, and a late outcome must not resurrect
                # board state (or its labeled gauge) for a dead pairing.
                return
            before = br.state
            br.record_success()
            self._sync(key_id, family, br, before)

    def record_failure(self, key_id: str, family: str) -> None:
        with self._lock:
            br = self._breakers.get((key_id, family))
            if br is None:  # forgotten pairing: see record_success
                return
            before = br.state
            br.record_failure(self._clock())
            self._sync(key_id, family, br, before)

    def abort_probe(self, key_id: str, family: str) -> None:
        with self._lock:
            br = self._breakers.get((key_id, family))
            if br is not None:
                br.abort_probe()  # never a transition: no _sync needed

    def state(self, key_id: str, family: str) -> str:
        with self._lock:
            br = self._breakers.get((key_id, family))
            return br.state if br is not None else CLOSED

    def retry_after(self, key_id: str, family: str) -> float | None:
        """The backoff hint for a caller refused by this pairing
        (ISSUE 12): OPEN -> the remaining cooldown (when it elapses the
        next allow becomes the half-open probe, so retrying then is not
        a guess but the sanctioned schedule); HALF_OPEN -> the full
        cooldown (a probe is in flight; if it fails the cooldown
        restarts, so anything shorter invites a thundering re-try at a
        breaker that may just have re-opened); CLOSED/unknown ->
        ``None`` (nothing to wait out).  Clamped at 0: a probe-ready
        breaker means "retry now"."""
        with self._lock:
            br = self._breakers.get((key_id, family))
            if br is None or br.state == CLOSED:
                return None
            if br.state == HALF_OPEN:
                return br.cooldown_s
            return max(0.0,
                       br.cooldown_s - (self._clock() - br.opened_at))

    def any_open(self) -> bool:
        """An open breaker still inside its cooldown — one of the
        brownout controller's two pressure signals (a failing backend
        family sheds load upstream at admission, not just at dispatch).

        OPEN past its cooldown does NOT count: such a breaker is merely
        probe-ready, and if the facade has demoted away from its family
        no traffic will ever route there to probe it — counting it
        would latch brownout on (and BATCH traffic off) forever on a
        service that is serving fine on the demoted-to family.  Open
        pressure means *actively failing*, not *historically failed*."""
        now = self._clock()
        with self._lock:
            if self._open == 0:  # the steady-state hot path: this runs
                # on every submit — don't scan the board when nothing
                # is open (the cooldown filter only matters when
                # something is)
                return False
            return any(
                br.state == OPEN and now - br.opened_at < br.cooldown_s
                for br in self._breakers.values())

    def forget(self, key_id: str) -> None:
        """Drop every family's breaker for ``key_id`` (unregistration —
        the pairing no longer exists).  Registry hot-swaps and LRU
        residency evictions deliberately do NOT route here: the failure
        history is about the backend family, which both survive."""
        with self._lock:
            for k, br in list(self._breakers.items()):
                if k[0] != key_id:
                    continue
                if br.state == OPEN:
                    # Keep the aggregate open gauge consistent with the
                    # board's contents, but do NOT route through _sync:
                    # unregistration is not a recovery, and counting a
                    # to=closed transition here would inflate the
                    # counter chaos_bench reads as proof the backend
                    # healed.
                    self._open -= 1
                    self._g_open.set(self._open)
                del self._breakers[k]
                # Cardinality hygiene: the pairing no longer exists, so
                # its labeled state series leaves the snapshot too —
                # under key churn (fresh keys per session) dead series
                # would otherwise accumulate in every snapshot forever.
                self._metrics.remove(labeled(
                    "serve_breaker_state", backend=k[1], key=k[0]))
