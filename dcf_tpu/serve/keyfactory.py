"""On-device key factory: ahead-of-demand keygen pools (ISSUE 11).

DCF keygen is the expensive offline phase of the protocol — and for
fresh-key-per-session traffic the serving tier used to pay it
synchronously inside every registration.  This module is the
provisioning pipeline that moves it off the registration clock:

* **Pools.**  A ``PoolSpec`` declares one class of pre-mintable keys —
  a FIXED comparison function (alphas/betas/bound for plain DCF, or a
  MIC interval set) whose per-session freshness lives entirely in the
  starting seeds.  Two sessions of the same pool evaluate the same
  f; their key material is independent because every minted bundle
  draws fresh OS-entropy seeds.  That is exactly the
  correlated-randomness dealer model: the function is public
  configuration, the shares are the secret, and shares can be minted
  before anyone asks.
* **Batched on-device minting.**  A refill packs ``refill_batch``
  sessions' keys onto the K axis of ONE device keygen call
  (``gen.gen_on_device`` — the walk's latency is per LEVEL, not per
  key, so B sessions cost one session's walk) and splits the result
  into per-session bundles.  On the hybrid family the factory uses
  ``gen.gen_on_device_with_planes``: both parties' staged narrow
  images come back from the same kernel walk and travel with the pool
  entry, so a claim's registration stages with zero host round-trip
  (``KeyRegistry`` ``dev_planes`` handoff).
* **Batched durable publish.**  With a ``KeyStore`` configured, every
  refill batch is published under the ``~pool/<name>/<seq>`` namespace
  through ``KeyStore.put_many`` — per-frame write-fsync-rename, ONE
  manifest flip for the whole batch.  A kill anywhere mid-refill
  leaves the previous manifest: old pool or new pool, never a torn
  one.  Entries become claimable only AFTER the flip
  (publish-to-servable ordering), so a claimed key is always a durable
  key.  Spent pool frames reclaim two ways.  A DURABLE claim folds
  the ``~pool/...`` delete into the SAME manifest flip that publishes
  the session frame (``KeyStore.put(..., drop=...)``): no crash
  window can leave both visible, so the same key material can never
  be claimed twice across a restart.  A NON-durable claim reclaims
  asynchronously (every claim nudges the worker; ``delete_many``, one
  flip per batch, also flushed at close and piggybacked on refills) —
  a crash inside that ~one-worker-tick window CAN resurrect a frame
  whose shares the dead session already received, i.e. a second
  session could be handed the same key material.  That residual
  window is deliberate: closing it would cost a per-claim fsync —
  comparable to the synchronous keygen the pool exists to avoid —
  and a session that needs the strict cross-crash no-reuse guarantee
  gets it for free by registering ``durable=True`` (the reclaim then
  rides the flip the durable registration pays anyway).
* **Claims.**  ``claim(pool)`` pops a pre-minted entry (a pool HIT:
  registration latency is a deque pop, not an n-level GGM walk).  On
  exhaustion it falls back to a SYNCHRONOUS single-session mint on the
  caller's clock — counted (``keyfactory_pool_misses_total``) and
  warned (``BackendFallbackWarning``), never silent — through the
  facade's HOST pipeline: the device walk wins on the K axis only, so
  a K-of-one synchronous mint is host-optimal by the router's own
  crossover rule.
* **Refill policy.**  The worker refills pools that fell below
  ``low_water`` back up to ``target_depth``, CRITICAL pools first
  (``serve.admission.Priority`` — ONE priority vocabulary, not a
  second policy), and under service brownout BATCH-priority pools are
  not refilled at all (pre-minting batch keys while the queue sheds is
  spending device time on the traffic being turned away).  Refills are
  gated by a per-pool circuit breaker (``serve.breaker.BreakerBoard``
  keyed ``(~pool/<name>, "keyfactory")`` on the factory's own board,
  so a dying keygen pipeline cannot also latch the SERVING brownout):
  repeated refill failures open it, claims drain the remaining pool /
  fall back counted, and the cooldown's half-open probe re-tests the
  pipeline.  The ``keyfactory.refill`` fault seam
  (``testing.faults``) fires at the head of each refill batch.
* **Warm restart.**  ``DcfService.restore_keys`` routes restored
  ``~pool/...`` frames back into their pools via ``adopt_restored``
  with generations preserved — zero re-keygen for already-published
  pool keys, the ISSUE-8 guarantee extended to the un-claimed half of
  the provisioning pipeline.  Restored entries carry no staged planes
  (device state does not survive a process) and stage from the host
  bundle on first use.

Driving modes mirror ``DcfService``: ``start()`` spawns the worker
thread (nudged by claims that drop a pool below low water, backstopped
by ``refill_interval_s`` polling); ``pump()`` runs one refill sweep
inline — the deterministic mode tests and benches drive.

Metrics: ``keyfactory_pool_depth{pool=...}`` /
``keyfactory_pool_hits_total`` / ``keyfactory_pool_misses_total`` /
``keyfactory_minted_keys_total`` / ``keyfactory_published_total`` /
``keyfactory_refills_total`` / ``keyfactory_refill_failures_total`` /
``keyfactory_restored_total`` / ``keyfactory_spent_reclaimed_total``.
"""

from __future__ import annotations

import threading
import warnings
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from dcf_tpu.errors import BackendFallbackWarning, ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.serve.admission import Priority, parse_priority
from dcf_tpu.serve.breaker import BreakerBoard
from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.spec import Bound
from dcf_tpu.testing.faults import fire

__all__ = ["PoolSpec", "KeyFactory", "POOL_NS", "pool_store_id",
           "parse_pool_store_id"]

#: Durable-store namespace for un-claimed pool frames.  ``~`` keeps the
#: namespace out of any sane caller-chosen key-id space and sorts after
#: letters, so pool frames cluster at the end of ``store.key_ids()``.
POOL_NS = "~pool/"


def pool_store_id(pool: str, seq: int) -> str:
    return f"{POOL_NS}{pool}/{seq}"


def parse_pool_store_id(key_id: str) -> tuple[str, int] | None:
    """``~pool/<name>/<seq>`` -> ``(name, seq)``; None for any other
    id (the service uses this to route restored frames)."""
    if not key_id.startswith(POOL_NS):
        return None
    pool, sep, seq = key_id[len(POOL_NS):].rpartition("/")
    if not sep or not pool or not seq.isdigit():
        return None
    return pool, int(seq)


@dataclass(frozen=True)
class PoolSpec:
    """One class of pre-mintable session keys (module docstring).

    ``alphas``/``betas``: the FIXED comparison function every session
    of this pool evaluates — uint8 [K, n_bytes] / [K, lam] for plain
    DCF pools (K keys per session, usually 1).  ``intervals``: set
    instead of ``alphas`` for MIC protocol pools — minted entries are
    then ``ProtocolBundle``s over these intervals with ``betas`` uint8
    [m, lam] per-interval outputs.  ``priority``: refill class under
    brownout (CRITICAL pools refill first; BATCH refill pauses).
    ``target_depth``/``low_water``: the refill hysteresis band;
    ``refill_batch``: sessions minted per device call (the K-axis
    packing the on-device keygen kernel scales with).  ``device``:
    route refills through ``gen.gen_on_device`` (None = the factory
    default, itself True).
    """

    name: str
    betas: np.ndarray
    alphas: np.ndarray | None = None
    intervals: tuple = ()
    bound: Bound = Bound.LT_BETA
    priority: Priority = Priority.NORMAL
    target_depth: int = 64
    low_water: int = 16
    refill_batch: int = 32
    device: bool | None = None

    def __post_init__(self):
        if not self.name or "/" in self.name:
            # api-edge: the name seeds the ~pool/<name>/<seq> store ids
            raise ValueError(
                f"pool name must be non-empty and '/'-free, "
                f"got {self.name!r}")
        object.__setattr__(self, "priority",
                           parse_priority(self.priority))
        object.__setattr__(self, "intervals",
                           tuple(tuple(pq) for pq in self.intervals))
        if (self.alphas is None) == (not self.intervals):
            raise ShapeError(
                f"pool {self.name!r} wants exactly one of alphas "
                "(plain DCF) or intervals (MIC)")
        betas = np.asarray(self.betas, dtype=np.uint8)
        object.__setattr__(self, "betas", betas)
        if self.alphas is not None:
            alphas = np.asarray(self.alphas, dtype=np.uint8)
            object.__setattr__(self, "alphas", alphas)
            if alphas.ndim != 2 or betas.shape != (alphas.shape[0],
                                                   betas.shape[-1]):
                raise ShapeError(
                    f"pool {self.name!r}: alphas must be [K, n_bytes] "
                    f"with betas [K, lam], got {alphas.shape} / "
                    f"{betas.shape}")
        elif betas.ndim != 2 or betas.shape[0] != len(self.intervals):
            raise ShapeError(
                f"pool {self.name!r}: betas must be "
                f"[{len(self.intervals)}, lam], got {betas.shape}")
        if self.target_depth < 1:
            # api-edge: pool-depth contract
            raise ValueError("target_depth must be >= 1")
        if not 0 <= self.low_water <= self.target_depth:
            # api-edge: refill-hysteresis contract
            raise ValueError(
                f"low_water must be in [0, target_depth="
                f"{self.target_depth}], got {self.low_water}")
        if self.refill_batch < 1:
            # api-edge: refill-batch contract
            raise ValueError("refill_batch must be >= 1")

    @property
    def keys_per_session(self) -> int:
        return (self.alphas.shape[0] if self.alphas is not None
                else 2 * len(self.intervals))

    def __repr__(self) -> str:  # betas are secret function values
        return (f"PoolSpec(name={self.name!r}, "
                f"kind={'mic' if self.intervals else 'plain'}, "
                f"keys_per_session={self.keys_per_session}, "
                f"priority={self.priority.name}, "
                f"depth={self.target_depth}, low={self.low_water}, "
                f"batch={self.refill_batch}, <function redacted>)")


class _Minted:
    """One pool entry: a pre-minted two-party session key, its staged
    planes (or None), its durable pool id + generation."""

    __slots__ = ("bundle", "protocol", "planes", "pool_id", "generation")

    def __init__(self, bundle: KeyBundle, protocol, planes,
                 pool_id: str, generation: int):
        self.bundle = bundle
        self.protocol = protocol
        self.planes = planes
        self.pool_id = pool_id
        self.generation = generation

    def __repr__(self) -> str:  # never key material — identity only
        return (f"_Minted(pool_id={self.pool_id!r}, "
                f"gen={self.generation}, "
                f"planes={self.planes is not None})")


class _Pool:
    """Spec + its entry deque + depth gauge (mutated under the factory
    lock only)."""

    __slots__ = ("spec", "entries", "seq", "depth_gauge")

    def __init__(self, spec: PoolSpec, depth_gauge):
        self.spec = spec
        # A deque, deliberately: claims pop the HEAD under the factory
        # lock on the registration hot path — a list's pop(0) would
        # shift O(depth) entries per claim.
        self.entries: deque[_Minted] = deque()
        self.seq = 0  # next ~pool/<name>/<seq>; advanced past restores
        self.depth_gauge = depth_gauge

    def __repr__(self) -> str:
        return f"_Pool({self.spec.name!r}, depth={len(self.entries)})"


@dataclass
class RefillReport:
    """One ``pump()`` sweep: per-pool minted counts and the pools a
    breaker or failure skipped (benches/tests read it; the worker
    ignores it)."""

    minted: dict = field(default_factory=dict)
    skipped: list = field(default_factory=list)
    failed: dict = field(default_factory=dict)


class KeyFactory:
    """Background ahead-of-demand keygen pools (module docstring).

    Construct through ``DcfService`` (which wires the store, metrics,
    clock and brownout signal); drive with ``start()``/``close()`` in
    production or ``pump()`` in tests and benches.
    """

    def __init__(self, dcf, *, registry, store=None,
                 metrics: Metrics | None = None, clock=None,
                 brownout=None, refill_interval_s: float = 0.05,
                 breaker_failures: int = 3,
                 breaker_cooldown_s: float = 5.0, rng=None):
        from dcf_tpu.utils.benchtime import monotonic

        self._dcf = dcf
        self._registry = registry
        self._store = store
        self.metrics = metrics if metrics is not None else Metrics()
        self._clock = clock if clock is not None else monotonic
        self._brownout = brownout if brownout is not None else (
            lambda: False)
        self.refill_interval_s = float(refill_interval_s)
        # dcflint: disable=determinism fresh key seeds MUST be
        # unpredictable (OS entropy); tests pass rng= to reproduce
        self._rng = rng if rng is not None else np.random.default_rng()
        # A numpy Generator is NOT thread-safe, and its draws here are
        # KEY MATERIAL: the refill worker and caller-thread sync mints
        # must serialize on it, not race it.
        self._rng_lock = threading.Lock()
        self.device_default = True
        # The factory's OWN breaker board: a dying keygen pipeline must
        # fail refills fast after the threshold, but must not count as
        # an open SERVING breaker (which would latch service brownout
        # and shed live traffic because provisioning is sick).
        self.breakers = BreakerBoard(
            failures_to_open=max(int(breaker_failures), 1),
            cooldown_s=breaker_cooldown_s, metrics=self.metrics,
            clock=self._clock)
        self._lock = threading.Lock()
        # One refill sweep at a time: pump() computes each pool's
        # deficit under _lock but mints outside it, so two concurrent
        # sweeps would each see the full deficit and overfill the pool
        # past target_depth (wasting device keygen and durable frames).
        self._pump_lock = threading.Lock()
        self._pools: dict[str, _Pool] = {}
        self._orphans: dict[str, list[_Minted]] = {}  # restored frames
        # whose pool spec is not declared yet; add_pool adopts them
        self._spent: list[str] = []  # claimed pool ids awaiting the
        # batched store reclaim (delete_many — one manifest flip)
        self._worker: threading.Thread | None = None
        self._wake = threading.Event()
        self._closed = False
        m = self.metrics
        self._c_hits = m.counter("keyfactory_pool_hits_total")
        self._c_misses = m.counter("keyfactory_pool_misses_total")
        self._c_minted = m.counter("keyfactory_minted_keys_total")
        self._c_published = m.counter("keyfactory_published_total")
        self._c_refills = m.counter("keyfactory_refills_total")
        self._c_refill_failures = m.counter(
            "keyfactory_refill_failures_total")
        self._c_restored = m.counter("keyfactory_restored_total")
        self._c_reclaimed = m.counter("keyfactory_spent_reclaimed_total")
        self._c_worker_errors = m.counter("keyfactory_worker_errors_total")

    def __repr__(self) -> str:
        return (f"KeyFactory(pools={sorted(self._pools)}, "
                f"durable={self._store is not None})")

    # -- pool management ----------------------------------------------------

    def add_pool(self, spec: PoolSpec) -> PoolSpec:
        """Declare a pool (idempotent for an identical spec is NOT
        supported — one name, one spec).  Validates the spec against
        the facade's geometry, adopts any restored-but-undeclared
        entries waiting under this name, and nudges the worker so the
        initial fill starts immediately."""
        lam, nb = self._dcf.lam, self._dcf.n_bytes
        if spec.betas.shape[-1] != lam:
            raise ShapeError(
                f"pool {spec.name!r}: betas lam {spec.betas.shape[-1]} "
                f"!= facade lam {lam}")
        if spec.alphas is not None and spec.alphas.shape[1] != nb:
            raise ShapeError(
                f"pool {spec.name!r}: alphas domain "
                f"{spec.alphas.shape[1]}B != facade domain {nb}B")
        with self._lock:
            if spec.name in self._pools:
                # api-edge: pool-name uniqueness contract
                raise ValueError(
                    f"pool {spec.name!r} already declared")
            pool = _Pool(spec, self.metrics.gauge(labeled(
                "keyfactory_pool_depth", pool=spec.name)))
            adopted = self._orphans.pop(spec.name, [])
            for minted in adopted:
                if self._adoptable(spec, minted):
                    pool.entries.append(minted)
                    pool.seq = max(
                        pool.seq,
                        parse_pool_store_id(minted.pool_id)[1] + 1)
                else:
                    self._spent.append(minted.pool_id)
            if self._store is not None:
                # A fresh process refilling an existing store must not
                # reuse live pool seqs (overwriting an unclaimed frame
                # wastes supply, even though put_many stays consistent).
                prefix = POOL_NS + spec.name + "/"
                for key_id in self._store.key_ids():
                    parsed = parse_pool_store_id(key_id)
                    if key_id.startswith(prefix) and parsed is not None:
                        pool.seq = max(pool.seq, parsed[1] + 1)
            self._pools[spec.name] = pool
            pool.depth_gauge.set(len(pool.entries))
        self._wake.set()
        return spec

    @staticmethod
    def _adoptable(spec: PoolSpec, minted: _Minted) -> bool:
        """A restored frame must still match its pool's declared
        geometry (a respec'd pool cannot serve old-shape supply)."""
        kb = minted.bundle
        return (kb.num_keys == spec.keys_per_session
                and kb.lam == spec.betas.shape[-1]
                and (minted.protocol is not None) == bool(spec.intervals))

    def pool_names(self) -> list[str]:
        with self._lock:
            return sorted(self._pools)

    def depth(self, pool: str) -> int:
        with self._lock:
            return len(self._require(pool).entries)

    def pool_manifest(self, pool: str) -> dict:
        """``{pool_id: generation}`` of the current entries (tests pin
        restored generations with it)."""
        with self._lock:
            return {m.pool_id: m.generation
                    for m in self._require(pool).entries}

    def _require(self, pool: str) -> _Pool:
        p = self._pools.get(pool)
        if p is None:
            # api-edge: unknown-pool lookup contract at the serve edge
            raise ValueError(
                f"no key pool declared under {pool!r} "
                f"(declared: {sorted(self._pools)})")
        return p

    # -- claims -------------------------------------------------------------

    def claim(self, pool: str) -> _Minted:
        """A fresh session key from ``pool``: the pre-minted head entry
        (pool HIT — a pop, not a keygen) or, on exhaustion, a
        synchronous single-session mint on the caller's clock (pool
        MISS — counted and warned; the silent path must never be what
        serves).  Thread-safe."""
        with self._lock:
            p = self._require(pool)
            minted = p.entries.popleft() if p.entries else None
            if minted is not None:
                p.depth_gauge.set(len(p.entries))
                self._c_hits.inc()
                if self._store is not None:
                    self._spent.append(minted.pool_id)
            spec = p.spec
        if minted is not None:
            # EVERY claim with a store nudges the worker, not just
            # low-water ones: the spent frame's reclaim flip must run
            # within one worker tick, because until it does a crash
            # would resurrect the frame at restore — for a NON-durable
            # claim that is the residual reuse window (bounded at
            # ~refill_interval_s; see the claim-reclaim notes in the
            # module docstring).  Durable claims have no window at all
            # (the session publish drops the frame in the same flip).
            if self._store is not None:
                self._wake.set()
            return minted
        self._c_misses.inc()
        warnings.warn(
            BackendFallbackWarning(
                f"keyfactory-pool:{pool}", "synchronous host keygen",
                None),
            stacklevel=3)
        minted = self._mint_sync(spec)
        self._wake.set()  # the pool is empty: refill now, not next tick
        return minted

    def _mint_sync(self, spec: PoolSpec) -> _Minted:
        """The pool-exhaustion fallback: ONE session minted through the
        facade's host pipeline (K=1 sessions gain nothing from the
        device walk — the K axis is its only lever), bit-exactly the
        key the pool would have handed out with the same seeds.  Never
        published (nothing was pooled) and never pooled (the caller
        takes it immediately).  Only the entropy draw holds the rng
        lock — concurrent misses must queue behind a seed spawn, not
        behind each other's full keygen walks (spawn derives a child
        from the full SeedSequence state, never a truncated seed: the
        draws are key material)."""
        with self._rng_lock:
            child = self._rng.spawn(1)[0]
        if spec.intervals:
            pb = self._dcf.mic(list(spec.intervals), spec.betas,
                               bound=spec.bound, rng=child)
            return _Minted(pb.keys, pb, None, "", 0)
        kb = self._dcf.gen(spec.alphas, spec.betas,
                           bound=spec.bound, rng=child)
        return _Minted(kb, None, None, "", 0)

    # -- refill -------------------------------------------------------------

    def pump(self) -> RefillReport:
        """One refill sweep, inline: every pool below its low-water
        mark is topped up to ``target_depth`` (one batched mint per
        ``refill_batch`` sessions), CRITICAL pools first, BATCH pools
        skipped under service brownout.  The deterministic driving
        mode; the worker thread calls this after each wake.
        Serialized: concurrent sweeps would double-mint each pool's
        deficit."""
        with self._pump_lock:
            return self._pump_locked()

    def _pump_locked(self) -> RefillReport:
        report = RefillReport()
        brown = self._brownout()
        with self._lock:
            todo = sorted(self._pools.values(),
                          key=lambda p: (p.spec.priority,
                                         p.spec.name))
            todo = [(p, p.spec, len(p.entries)) for p in todo]
        for pool, spec, depth in todo:
            # Refill triggers when the pool is EMPTY or strictly below
            # its low-water mark, and tops up to target_depth — the
            # hysteresis band keeps steady-state claims from minting
            # one key at a time (low_water=0: only an empty pool
            # refills).
            if depth and depth >= spec.low_water:
                continue
            if brown and spec.priority is Priority.BATCH:
                report.skipped.append(spec.name)
                continue
            board_key = POOL_NS + spec.name
            if not self.breakers.allow(board_key, "keyfactory"):
                report.skipped.append(spec.name)
                continue
            minted_total = 0
            try:
                while True:
                    with self._lock:
                        want = spec.target_depth - len(pool.entries)
                    if want <= 0:
                        break
                    count = min(want, spec.refill_batch)
                    fire("keyfactory.refill", spec.name, count)
                    minted_total += self._refill_batch(pool, spec, count)
            except Exception as e:  # fallback-ok: a refill failure is
                # contained to this pool and this sweep — the worker
                # must survive, the breaker records it, and claims keep
                # serving from the remaining pool / the counted
                # synchronous fallback
                self._c_refill_failures.inc()
                self.breakers.record_failure(board_key, "keyfactory")
                report.failed[spec.name] = repr(e)
            else:
                if minted_total:
                    self.breakers.record_success(board_key, "keyfactory")
            finally:
                # A probe slot the gate sanctioned must never wedge
                # HALF_OPEN if the sweep resolved no outcome (want<=0).
                self.breakers.abort_probe(board_key, "keyfactory")
            if minted_total:
                report.minted[spec.name] = minted_total
        self._flush_spent()
        return report

    def _refill_batch(self, pool: _Pool, spec: PoolSpec,
                      count: int) -> int:
        """Mint + publish + pool ``count`` sessions as ONE K-packed
        keygen call and ONE manifest flip.  Entries become claimable
        only after the publish returns: publish-to-servable ordering."""
        ks = spec.keys_per_session
        if spec.intervals:
            from dcf_tpu.protocols.keygen import interval_session_material

            # The ONE derivation gen_interval_bundle uses: pooled MIC
            # keys and the sync-mint fallback must share it, or the
            # combine convention could fork between hit and miss.
            alphas, session_betas, masks = interval_session_material(
                list(spec.intervals), spec.betas, self._dcf.n_bytes,
                spec.bound)
        else:
            alphas, session_betas, masks = spec.alphas, spec.betas, None
        al = np.tile(alphas, (count, 1))
        bt = np.tile(session_betas, (count, 1))
        from dcf_tpu.gen import (
            gen_on_device,
            gen_on_device_with_planes,
            random_s0s,
        )

        with self._rng_lock:
            s0s = random_s0s(count * ks, self._dcf.lam, self._rng)
        use_device = (spec.device if spec.device is not None
                      else self.device_default)
        planes = None
        if use_device:
            if self._want_planes():
                kb_all, planes = gen_on_device_with_planes(
                    self._dcf.lam, self._dcf.cipher_keys, al, bt, s0s,
                    spec.bound)
            else:
                kb_all = gen_on_device(
                    self._dcf.lam, self._dcf.cipher_keys, al, bt, s0s,
                    spec.bound)
        else:
            kb_all = self._dcf.gen(al, bt, s0s=s0s, bound=spec.bound)
        self._c_minted.inc(count * ks)
        gens = self._registry.mint_generations(count)
        with self._lock:
            seq0 = pool.seq
            pool.seq += count
        entries = []
        for i in range(count):
            kb = _slice_keys(kb_all, i * ks, (i + 1) * ks)
            proto = None
            if masks is not None:
                from dcf_tpu.protocols.keygen import ProtocolBundle

                proto = ProtocolBundle(keys=kb, combine_masks=masks,
                                       bound=spec.bound)
            entry_planes = (None if planes is None else
                            _slice_planes_pair(planes, i * ks,
                                               (i + 1) * ks))
            entries.append(_Minted(kb, proto, entry_planes,
                                   pool_store_id(spec.name, seq0 + i),
                                   gens[i]))
        if self._store is not None:
            published = self._store.put_many(
                [(m.pool_id, m.bundle, m.protocol, m.generation)
                 for m in entries])
            self._c_published.inc(published)
        with self._lock:
            pool.entries.extend(entries)
            pool.depth_gauge.set(len(pool.entries))
        self._c_refills.inc()
        return count

    def _want_planes(self) -> bool:
        """Staged-plane handoff applies when the serving facade stages
        the single-device hybrid image (the only backend that can adopt
        the keygen kernel's plane layout verbatim)."""
        return (self._dcf.lam >= 48 and self._dcf.lam % 16 == 0
                and self._dcf.mesh is None
                and self._dcf.backend_name == "hybrid")

    def reclaim_spent(self) -> None:
        """Flush the pending spent-frame reclaim now (ONE
        ``delete_many`` flip).  Normally rides each worker sweep;
        public so harnesses can separate the reclaim flip from the
        publish flip they are timing (``keyfactory_bench``)."""
        self._flush_spent()

    def _flush_spent(self) -> None:
        """Batched reclaim of claimed pool frames (ONE manifest flip).
        A failed flip re-queues the batch — the claimed ids must not be
        lost to a transient store failure, or the frames would sit in
        the manifest forever and resurrect at every restore."""
        if self._store is None:
            return
        with self._lock:
            spent, self._spent = self._spent, []
        if not spent:
            return
        try:
            self._c_reclaimed.inc(self._store.delete_many(spent))
        except Exception:  # fallback-ok: re-raised below — this handler
            # only re-queues the batch so a transient store failure
            # cannot lose the claimed ids (which would resurrect the
            # frames at every restore); it swallows nothing
            with self._lock:
                self._spent = spent + self._spent
            raise

    # -- warm restart -------------------------------------------------------

    def adopt_restored(self, report, registry) -> int:
        """Route restored ``~pool/...`` frames out of the serving
        registry and back into their pools, generations preserved
        (ISSUE 11: the un-claimed pool supply survives a crash with
        zero re-keygen) — moving them from ``report.restored`` to
        ``report.repooled``.  Frames for pools not yet declared wait
        in an orphan stash that ``add_pool`` adopts (also reported
        repooled: they are factory-held supply).  Frames that no
        longer match their pool's declared geometry are RECLAIMED —
        reported in neither map, observable through the store's delete
        metrics (a respec'd pool cannot serve old-shape supply).
        Returns the number of entries re-pooled or stashed."""
        adopted = 0
        for key_id in sorted(report.restored):
            parsed = parse_pool_store_id(key_id)
            if parsed is None:
                continue
            name, seq = parsed
            generation = report.restored.pop(key_id)
            bundle, protocol, _gen = registry.snapshot(key_id)
            registry.unregister(key_id)  # pool supply is not servable
            minted = _Minted(bundle, protocol, None, key_id, generation)
            with self._lock:
                pool = self._pools.get(name)
                if pool is None:
                    self._orphans.setdefault(name, []).append(minted)
                elif self._adoptable(pool.spec, minted):
                    pool.entries.append(minted)
                    pool.seq = max(pool.seq, seq + 1)
                    pool.depth_gauge.set(len(pool.entries))
                else:
                    self._spent.append(key_id)
                    continue
            report.repooled[key_id] = generation
            adopted += 1
        if adopted:
            self._c_restored.inc(adopted)
        return adopted

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "KeyFactory":
        """Spawn the refill worker (idempotent and thread-safe — both
        ``DcfService.start`` and ``add_pool`` call this, and a racing
        pair must not spawn duplicate workers; a factory with no pools
        idles on the interval backstop until one is declared)."""
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._closed = False
                self._worker = threading.Thread(
                    target=self._worker_loop, name="dcf-keyfactory",
                    daemon=True)
                self._worker.start()
        return self

    def _worker_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self.refill_interval_s)
            self._wake.clear()
            if self._closed:
                return
            try:
                self.pump()
            except Exception:  # fallback-ok: the refill worker must
                # outlive ANY sweep failure (pump already contains
                # per-pool failures; this is the belt for e.g. a dying
                # store's reclaim flip) — COUNTED, never silent, and
                # the next tick retries
                self._c_worker_errors.inc()

    def close(self) -> None:
        """Stop the worker and flush the pending spent-frame reclaim."""
        self._closed = True
        self._wake.set()
        worker = self._worker
        if worker is not None and worker.is_alive() \
                and worker is not threading.current_thread():
            worker.join()
        self._flush_spent()


def _slice_keys(kb: KeyBundle, lo: int, hi: int) -> KeyBundle:
    """Rows ``[lo, hi)`` of a K-packed bundle as an independent bundle
    (copies — a pool entry must not pin the whole refill batch's
    arrays alive)."""
    return KeyBundle(
        s0s=kb.s0s[lo:hi].copy(), cw_s=kb.cw_s[lo:hi].copy(),
        cw_v=kb.cw_v[lo:hi].copy(), cw_t=kb.cw_t[lo:hi].copy(),
        cw_np1=kb.cw_np1[lo:hi].copy())


def _slice_planes_pair(planes: dict, lo: int, hi: int) -> dict:
    """Key-axis slice of a both-parties plane pair (every plane is
    K-major: see ``ops.pallas_keygen.PallasKeyGen.staged_planes``).
    ``gen_with_planes_pair`` shares the correction-word arrays between
    the two party dicts BY IDENTITY; the slice preserves that sharing
    (detected by identity, so it tracks the staged layout instead of a
    hardcoded name list) — slicing a shared plane once per party would
    materialize two device copies of the same image per pool entry."""
    shared = {name: arr[lo:hi] for name, arr in planes[0].items()
              if planes[1].get(name) is arr}
    return {b: {name: (shared[name] if name in shared else arr[lo:hi])
                for name, arr in planes[b].items()}
            for b in planes}
