"""Autonomous ring membership for the pod serving tier (ISSUE 15).

PR 14 closed the self-healing loop for a FIXED ring: the prober walks a
dead shard DOWN, promotion serves its keys from the replica, and
anti-entropy gates its re-admission.  What stayed manual was the ring
itself — ``set_ring`` was operator-invoked, so a shard that stayed DOWN
left every victim key served from a LONE promoted replica with no
re-replication (a second failure loses live traffic), and planned
capacity changes required a human to swap the ring and hope no
in-flight registration raced it.  This module is the control plane that
closes that loop: membership DRIVEN by health, with every change fenced
the way PR 14 fenced generations.

``MembershipController`` owns three reconfiguration verbs plus the
fence that makes them safe:

* **Auto-eject** (``eject``, driven by ``pump``): a shard the prober
  has held DOWN for ``eject_grace_s`` is removed from the ring —
  ``ShardMap.without_host``, so exactly its keys move, each to the
  host rendezvous already ranked next.  BEFORE the swap commits, every
  frame the victim held is re-replicated to its new placement: durable
  frames via ``KeyStore.replicate_to`` (the victim's on-disk store
  survives its process and is a valid source — that is what durability
  buys), live keys via the existing DIGEST/SYNC + REGISTER anti-entropy
  machinery (``Replicator.anti_entropy`` against the POST-eject ring),
  generations preserved and fenced throughout.  The grace period is
  the flap filter: promotion already serves the victim's keys the
  moment the prober says DOWN, so ejection is never racing against
  availability — it restores the REPLICATION FACTOR, which is why it
  can afford to wait out a reboot.

* **Graceful join** (``join``): a new host is warmed BEFORE it is
  admitted — the controller dials it (``DcfRouter.preconnect``), runs
  the anti-entropy pull against the PROSPECTIVE ring (every key the
  new ring will place on it arrives with its owner's generation), and
  only then swaps the map.  The first routed request therefore finds a
  warm shard: no cold-miss storm, no window where placement names a
  host that holds nothing.  A registration racing the warm is caught
  by a second, post-admission convergence pass (strictly-newer pulls
  make it idempotent).

* **Graceful drain** (``drain``): planned decommission, in three
  phases — migrate every frame the host holds to its new-ring
  placement (the draining host itself is the primary SOURCE: it is
  alive, this is not failover), swap the ring (new placements stop the
  moment the swap commits; a hot-swap racing the migration is caught
  by the same post-swap convergence pass), then hold the host's pool
  open for ``drain_grace_s`` so in-flight relayed requests — which
  keep the old map reference by design — complete against it before
  ``forget_host`` drops the link.  Only then is the process safe to
  stop (``serve_host`` SIGTERM drains; see the CLI).

* **Epoch fencing**: every commit mints a strictly-monotonic ring
  epoch (``router.set_ring(..., epoch=)``).  Forwarded DCFE frames
  carry it; shards track the observed maximum and refuse older ones
  typed (``RingEpochError`` / ``E_EPOCH`` — ``serve.edge``).  The
  generation fence makes an old partition side unable to roll a KEY
  back; the epoch fence makes a stale router unable to serve a
  conflicting PLACEMENT — same discipline, one level up.  Probes
  disseminate the epoch, so the pod converges within about one probe
  interval of a commit.

Safety rules: one membership change at a time (serialized on the
controller's lock); auto-eject refuses to shrink the ring below
``min_hosts`` (promotion keeps serving — losing the last replica to a
bookkeeping action would be self-inflicted data loss) and refuses
while any OTHER shard is DOWN (a double failure is a recovery
scenario, not a reconfiguration scenario — migrating with a source
missing could silently halve the replication it was meant to
restore); a migration pass that cannot reach a needed source raises
and the change is retried on a later pump, the same
conservative-direction rule the PR 14 recovery gate applies.

Driving modes mirror ``HealthProber``: ``start()`` spawns a worker
evaluating the eject grace and finishing drains every
``poll_interval_s``; ``pump()`` runs one evaluation inline — the
deterministic mode, on the injectable clock.  Every committed change
is a typed ``MembershipEvent`` and a metrics write
(``membership_*`` series — see ``serve.metrics``).

Capacity delegation (ISSUE 16, ``serve.capacity``): the demand-driven
``CapacityController`` scales the ring through the SAME two verbs —
``join`` for scale-out (warm-before-admit), ``drain`` for scale-in
(durable migration) — so autoscaling inherits every fence and safety
rule above instead of growing a second reconfiguration path.  Its
rails read this controller's state: ``eject_in_flight`` reports
whether the health plane is mid-failure (a DOWN ring member, or an
eject grace already running) so a scaling change never races a health
eject, and ``store_for`` hands back a drained host's recorded store
so the host can return to the standby pool intact.

Secret hygiene: migrations move whole DCFK frames (key material) —
this module logs names, hosts, epochs and counts only, and the frame
buffers stay inside the edge-client calls.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from dcf_tpu.errors import BackendUnavailableError, KeyQuarantinedError
from dcf_tpu.serve.health import DOWN
from dcf_tpu.serve.metrics import labeled
from dcf_tpu.serve.shardmap import ShardMap, ShardSpec
from dcf_tpu.testing.faults import fire

__all__ = ["MembershipController", "MembershipEvent"]

#: Key-factory pool frames (``~pool/<name>/<seq>``) are host-local
#: pre-minted supply, not placed serving keys: they never migrate.
_POOL_PREFIX = "~pool/"


@dataclass(frozen=True)
class MembershipEvent:
    """One committed (or completed) membership change: ``kind`` is
    ``eject`` / ``join`` / ``drain`` / ``drain-complete``, ``epoch``
    the ring epoch it committed under (0 for ``drain-complete`` — the
    deferred forget commits nothing), ``migrated`` how many live
    frames the convergence passes moved, ``at`` the injectable-clock
    time."""

    kind: str
    host_id: str
    epoch: int
    migrated: int
    at: float


class MembershipController:
    """Health-driven ring membership over one ``DcfRouter`` (see the
    module docstring).

    ``router``: the pod router whose ring this controller owns —
    after construction, ``set_ring`` belongs to the controller (an
    operator swap behind its back would fork the epoch sequence).
    ``stores``: optional ``{host_id: KeyStore}`` mapping for the
    durable half of migrations (the pod provisioning layout —
    ``pod_bench`` hands the same stores it provisioned; absent hosts
    simply get no durable copy, the live REGISTER path still serves).
    ``eject_grace_s``: how long a shard must stay DOWN before
    auto-ejection.  ``drain_grace_s``: how long a drained host's pool
    outlives the swap for in-flight relays.  ``min_hosts``: the floor
    auto-eject will not shrink the ring below (explicit ``drain`` may
    go to 1 — a planned decommission is the operator's call).
    ``clock``: the injectable clock (defaults to the router's).
    """

    def __init__(self, router, *, stores: dict | None = None,
                 eject_grace_s: float = 5.0,
                 drain_grace_s: float = 2.0, min_hosts: int = 2,
                 clock=None, timeout_s: float = 30.0,
                 poll_interval_s: float = 0.5,
                 max_events: int = 256):
        if eject_grace_s < 0 or drain_grace_s < 0:
            # api-edge: controller config contract
            raise ValueError(
                f"eject_grace_s/drain_grace_s must be >= 0, got "
                f"{eject_grace_s}/{drain_grace_s}")
        if min_hosts < 1:
            # api-edge: controller config contract — a ring of zero
            # hosts cannot place anything
            raise ValueError(f"min_hosts must be >= 1, got {min_hosts}")
        if poll_interval_s <= 0:
            # api-edge: controller config contract
            raise ValueError(
                f"poll_interval_s must be > 0, got {poll_interval_s}")
        self._router = router
        # guarded-by: _op_lock
        self._stores = dict(stores) if stores else {}
        self.eject_grace_s = float(eject_grace_s)
        self.drain_grace_s = float(drain_grace_s)
        self.min_hosts = int(min_hosts)
        self.poll_interval_s = float(poll_interval_s)
        self._timeout_s = float(timeout_s)
        self._clock = clock if clock is not None else router._clock
        self._max_events = int(max_events)
        # ONE membership change at a time: eject/join/drain serialize
        # here, and pump's scan re-checks state under it — two racing
        # changes could each compute a ring that forgets the other's.
        self._op_lock = threading.Lock()
        self._state_lock = threading.Lock()
        # guarded-by: _state_lock
        self._down_since: dict[str, float] = {}
        # guarded-by: _state_lock
        self._draining: dict[str, float] = {}  # host -> forget deadline
        # guarded-by: _state_lock
        self._lost_counted: set[str] = set()  # keys already in the
        #                                       lost counter (audit
        #                                       polling must not
        #                                       re-count a loss)
        # guarded-by: _state_lock
        self._events: list[MembershipEvent] = []
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        m = router.metrics
        self._c_ejects = m.counter("membership_ejections_total")
        self._c_joins = m.counter("membership_joins_total")
        self._c_drains = m.counter("membership_drains_total")
        self._c_migrated = m.counter("membership_migrated_frames_total")
        self._c_durable = m.counter(
            "membership_durable_replications_total")
        self._c_op_failures = m.counter(
            "membership_change_failures_total")
        self._c_eject_skipped = m.counter(
            "membership_eject_skipped_total")
        self._c_store_unreachable = m.counter(
            "membership_store_unreachable_total")
        self._c_lost = m.counter("membership_lost_keys_total")
        self._g_ring_size = m.gauge("membership_ring_size")
        self._g_draining = m.gauge("membership_draining_hosts")
        self._g_ring_size.set(len(router.map))

    # -- events -------------------------------------------------------

    def _record(self, kind: str, host_id: str, epoch: int,
                migrated: int) -> MembershipEvent:
        ev = MembershipEvent(kind, host_id, int(epoch), int(migrated),
                             self._clock())
        with self._state_lock:
            self._events.append(ev)
            del self._events[:-self._max_events]
        return ev

    def events(self) -> list:
        """Drain the committed-change events observed so far (bounded,
        like ``HealthProber.events`` — the metrics are the durable
        record)."""
        with self._state_lock:
            out, self._events = self._events, []
            return out

    def draining(self) -> dict:
        """``{host_id: forget_deadline}`` for drains whose in-flight
        grace has not elapsed yet (``pump`` completes them)."""
        with self._state_lock:
            return dict(self._draining)

    def eject_in_flight(self) -> bool:
        """True while the health plane is mid-failure: some ring
        member is DOWN, or an eject grace is already being tracked
        (ISSUE 16 safety rail — the capacity controller must never
        commit a scaling change concurrent with a health-driven eject;
        two changes racing would each compute a ring that forgets the
        other's, and a surge verdict during an outage is promotion
        noise, not demand)."""
        ring_ids = set(self._router.map.host_ids())
        states = self._router.health.states()
        if any(st == DOWN and h in ring_ids
               for h, st in states.items()):
            return True
        with self._state_lock:
            return any(h in ring_ids for h in self._down_since)

    def store_for(self, host_id: str):
        """The ``KeyStore`` recorded for ``host_id`` (None if never
        provisioned here) — how the capacity controller returns a
        drained host to the standby pool with its store attached."""
        # dcflint: disable=guarded-by single dict .get() under the GIL,
        # and the capacity controller calls this only AFTER its drain
        # committed — the entry it reads cannot be mid-mutation
        return self._stores.get(host_id)

    # -- the control loop ---------------------------------------------

    def pump(self) -> list:
        """One control round inline (the deterministic driving mode):
        finish drains whose grace elapsed, track DOWN durations, and
        auto-eject every shard DOWN past the grace.  Returns the list
        of ``MembershipEvent``s this round committed."""
        out: list = []
        now = self._clock()
        with self._state_lock:
            due = [h for h, t in self._draining.items() if now >= t]
        for host_id in due:
            with self._op_lock:
                # A drained host that re-JOINED within its grace is a
                # ring member again — its retained pool is the member's
                # pool now, and forgetting it would sever a live link
                # (forget_host refuses exactly that).  The drain window
                # still ends either way.
                if host_id not in self._router.map:
                    self._router.forget_host(host_id)
            with self._state_lock:
                self._draining.pop(host_id, None)
                self._g_draining.set(len(self._draining))
            out.append(self._record("drain-complete", host_id, 0, 0))
        states = self._router.health.states()
        ring_ids = set(self._router.map.host_ids())
        with self._state_lock:
            for host_id in list(self._down_since):
                if host_id not in ring_ids \
                        or states.get(host_id) != DOWN:
                    del self._down_since[host_id]
            overdue = []
            for host_id, st in states.items():
                if st != DOWN or host_id not in ring_ids:
                    continue
                since = self._down_since.setdefault(host_id, now)
                if now - since >= self.eject_grace_s:
                    overdue.append(host_id)
        for host_id in overdue:
            down_ids = {h for h, st in
                        self._router.health.states().items()
                        if st == DOWN and h in self._router.map}
            if len(self._router.map) - 1 < self.min_hosts \
                    or len(down_ids) > 1:
                # Never below the floor, never during a multi-failure:
                # promotion keeps the keys serving; ejecting here
                # would trade availability bookkeeping for replication
                # the surviving ring cannot actually rebuild.
                self._c_eject_skipped.inc()
                continue
            try:
                out.append(self.eject(host_id))
            except Exception:  # fallback-ok: a failed change (a
                # source peer died mid-migration) was counted by
                # eject itself and is retried on a later pump — the
                # ring stays on the last committed epoch, promotion
                # keeps serving
                pass
        return out

    # -- the three verbs ----------------------------------------------

    def eject(self, host_id: str) -> MembershipEvent:
        """Remove a (presumed dead) shard from the ring, restoring the
        replication factor of every key it held BEFORE the swap
        commits: durable frames via ``KeyStore.replicate_to`` (the
        victim's store outlives its process), live keys via the
        anti-entropy pull against the post-eject ring.  Commits under
        a fresh epoch.  Also callable directly — the operator's
        force-eject; the grace only gates the AUTOMATIC path."""
        with self._op_lock:
            router = self._router
            if host_id not in router.map:
                # api-edge: membership contract (ejecting an unknown
                # host is a caller bookkeeping bug)
                raise ValueError(
                    f"host {host_id!r} is not in the ring "
                    f"({router.map.host_ids()})")
            new_ring = router.map.without_host(host_id)
            try:
                self._replicate_durable(new_ring, exclude={host_id})
                # Live convergence BEFORE the swap: every remaining
                # member pulls the frames the new ring places on it
                # that it does not hold yet (the victim's keys, from
                # their surviving replicas) — so the swap lands on a
                # ring that is already whole.  The victim is excluded
                # as a source (it is DOWN).
                peers = [h for h in router.map.host_ids()
                         if h != host_id]
                migrated = self._converge(peers, new_ring, peers,
                                          exclude={host_id})
            except Exception:  # fallback-ok: counted, re-raised — an
                # aborted change leaves the ring on its last committed
                # epoch; promotion keeps serving and a later pump
                # retries
                self._c_op_failures.inc()
                raise
            epoch = router.ring_epoch + 1
            router.set_ring(new_ring, epoch=epoch)
            # Post-swap sweep: a registration that raced the
            # pre-commit passes landed on the OLD placement; strictly-
            # newer pulls converge it onto the new one (idempotent —
            # an already-whole ring pulls nothing).  The change is
            # COMMITTED at this point: a sweep failure is counted and
            # left to a later convergence pass (anti-entropy is
            # idempotent) — raising here would skip the bookkeeping
            # below and report a committed change as aborted.
            try:
                migrated += self._converge(peers, new_ring, peers,
                                           exclude={host_id})
            except Exception:  # fallback-ok: counted; see above
                self._c_op_failures.inc()
            with self._state_lock:
                self._down_since.pop(host_id, None)
            self._c_ejects.inc()
            self._c_migrated.inc(migrated)
            self._g_ring_size.set(len(new_ring))
            return self._record("eject", host_id, epoch, migrated)

    def join(self, spec: ShardSpec, store=None) -> MembershipEvent:
        """Admit a new (or returning) host: dial it, warm it through
        the anti-entropy SYNC path against the PROSPECTIVE ring, and
        only then commit the swap under a fresh epoch — the first
        routed request finds every key the new ring places on the
        host already registered, generations preserved (no cold-miss
        storm).  ``store``: the host's ``KeyStore``, recorded for the
        durable half of future migrations.  A warm that fails aborts
        the join typed (counted); the ring is untouched."""
        with self._op_lock:
            router = self._router
            if spec.host_id in router.map:
                # api-edge: membership contract — re-admitting a live
                # member is a bookkeeping bug (an address change is
                # set_ring's job, not a join)
                raise ValueError(
                    f"host {spec.host_id!r} is already in the ring")
            if store is not None:
                self._stores[spec.host_id] = store
            prospective = router.map.with_host(spec)
            router.preconnect(spec)
            try:
                self._replicate_durable(prospective, exclude=set())
                migrated = self._converge(
                    [spec.host_id], prospective,
                    router.map.host_ids(), exclude=set())
            except Exception:  # fallback-ok: an aborted join must not
                # leave a half-warmed host admitted OR a dangling
                # link — the caller retries once the pod is reachable
                # again
                self._c_op_failures.inc()
                router.forget_host(spec.host_id)
                raise
            epoch = router.ring_epoch + 1
            router.set_ring(prospective, epoch=epoch)
            # The join-racing-registration sweep: a key registered
            # while the warm ran placed on the OLD ring; pull anything
            # strictly newer now that the newcomer is admitted.  The
            # host IS admitted at this point: a sweep failure is
            # counted and healed by a later convergence pass, never
            # re-raised (that would report a committed join as aborted
            # and make a retry die on the already-in-the-ring check).
            try:
                migrated += self._converge(
                    [spec.host_id], prospective,
                    [h for h in prospective.host_ids()
                     if h != spec.host_id], exclude=set())
            except Exception:  # fallback-ok: counted; see above
                self._c_op_failures.inc()
            self._c_joins.inc()
            self._c_migrated.inc(migrated)
            self._g_ring_size.set(len(prospective))
            return self._record("join", spec.host_id, epoch, migrated)

    def drain(self, host_id: str) -> MembershipEvent:
        """Gracefully decommission a LIVE host: migrate every frame it
        holds to its new-ring placement (the draining host is the
        primary source — this is planned, not failover), swap the ring
        under a fresh epoch (new placements stop at the commit), and
        keep the host's pool open for ``drain_grace_s`` so in-flight
        relayed requests complete against it; ``pump`` finishes the
        forget.  The process is safe to SIGTERM once ``draining()``
        no longer names it (``serve_host`` then drains its own queue
        and exits 0)."""
        with self._op_lock:
            router = self._router
            if host_id not in router.map:
                # api-edge: membership contract
                raise ValueError(
                    f"host {host_id!r} is not in the ring "
                    f"({router.map.host_ids()})")
            if len(router.map) < 2:
                # api-edge: membership contract — draining the last
                # host would leave an empty ring with nowhere to
                # migrate TO; stop the pod instead
                raise ValueError(
                    "cannot drain the last host in the ring")
            new_ring = router.map.without_host(host_id)
            targets = new_ring.host_ids()
            sources = router.map.host_ids()  # the drainee included
            try:
                self._replicate_durable(new_ring, exclude=set())
                migrated = self._converge(targets, new_ring, sources,
                                          exclude=set())
            except Exception:  # fallback-ok: counted, re-raised — an
                # aborted drain leaves the host a full member on the
                # last committed epoch
                self._c_op_failures.inc()
                raise
            epoch = router.ring_epoch + 1
            router.set_ring(new_ring, epoch=epoch, retain={host_id})
            # Drain-racing-hot-swap sweep: a re-registration that
            # landed on the drainee between the migration pass and the
            # commit is strictly newer — pull it across now.  The swap
            # is COMMITTED: a sweep failure is counted and healed by a
            # later pass, never re-raised — the drain-grace bookkeeping
            # below MUST run or pump never forgets the retained pool
            # (a leaked link probed forever) and the operator never
            # learns the host is safe to stop.
            try:
                migrated += self._converge(targets, new_ring, sources,
                                           exclude=set())
            except Exception:  # fallback-ok: counted; see above
                self._c_op_failures.inc()
            with self._state_lock:
                self._draining[host_id] = self._clock() \
                    + self.drain_grace_s
                self._g_draining.set(len(self._draining))
            self._c_drains.inc()
            self._c_migrated.inc(migrated)
            self._g_ring_size.set(len(new_ring))
            return self._record("drain", host_id, epoch, migrated)

    # -- migration machinery ------------------------------------------

    def _converge(self, targets, ring: ShardMap, sources,
                  exclude: set) -> int:
        """Pull every frame ``ring`` places on each target that the
        target is behind on, from ``sources`` (strictly-newer,
        placement-filtered at the sender — ``Replicator.anti_entropy``
        with the membership override).  DOWN sources are skipped via
        ``peer_ok`` (their keys come from their replicas); a REACHABLE
        source failing mid-exchange raises, aborting the change — the
        conservative direction, same as the recovery gate."""
        router = self._router
        fire("membership.migrate", sorted(targets), len(ring))
        down = {h for h, st in router.health.states().items()
                if st == DOWN}
        moved = 0
        for target in targets:
            if target in exclude or target in down:
                continue
            moved += router.replicator.anti_entropy(
                target, ring=ring,
                peers=[h for h in sources if h != target],
                peer_ok=lambda h: h not in down and h not in exclude,
                timeout=self._timeout_s)
        return moved

    # holds-lock: _op_lock
    def _replicate_durable(self, ring: ShardMap, exclude: set) -> int:
        """The durable half of a migration: for every frame any known
        store holds, ensure each store of the frame's NEW placement
        holds it at the newest stored generation
        (``KeyStore.replicate_to`` — atomic publish, monotonic
        guard, bounded transient-retry).  ``exclude`` hosts are dead
        PROCESSES, not dead disks: their stores remain valid sources
        (that is what the durable tier is for), they are only never a
        DESTINATION.  Key-factory ``~pool/`` frames are host-local
        supply and never move.  A key no reachable store holds is
        counted lost (``membership_lost_keys_total``) — the bench
        gates it at zero."""
        if not self._stores:
            return 0
        digests: dict[str, dict] = {}
        for host_id, st in self._stores.items():
            try:
                digests[host_id] = st.digest()
            except OSError:
                # A store whose digest cannot even be READ (dead disk
                # or mount — distinct from a dead PROCESS, whose
                # surviving on-disk store is the normal eject source)
                # is neither a source nor a destination this pass:
                # counted and skipped.  Aborting on it would wedge
                # every future membership change on a disk that may
                # never return, while promotion keeps the live keys
                # serving — the conservative-direction rule applies to
                # REACHABLE sources failing mid-copy, not to hosts
                # that are provably gone.
                self._c_store_unreachable.inc()
        newest: dict[str, int] = {}
        for digest in digests.values():
            for key_id, gen in digest.items():
                if key_id.startswith(_POOL_PREFIX):
                    continue
                if gen > newest.get(key_id, 0):
                    newest[key_id] = gen
        copied = 0
        for key_id in sorted(newest):
            gen = newest[key_id]
            # EVERY holder at the newest generation is a source
            # candidate: one failing (exhausted retries, quarantined
            # frame) falls through to the next replica before the
            # change aborts.
            srcs = sorted(h for h, d in digests.items()
                          if d.get(key_id) == gen)
            for dst in sorted(ring.placement_ids(
                    key_id, self._router.replicas)):
                if dst in exclude or dst not in digests:
                    continue
                if digests[dst].get(key_id, 0) >= gen:
                    continue
                done, last_exc = False, None
                for src in srcs:
                    if src == dst:
                        continue
                    try:
                        self._stores[src].replicate_to(
                            self._stores[dst], key_id)
                        done = True
                        break
                    except (OSError, BackendUnavailableError,
                            KeyQuarantinedError) as e:
                        # fallback-ok: next holder; re-raised below if
                        # every one fails
                        last_exc = e
                if done:
                    self._c_durable.inc()
                    copied += 1
                elif last_exc is not None:
                    # Every holder failed: the conservative abort —
                    # the change retries on a later pump.
                    raise last_exc
        return copied

    def lost_keys(self, exclude: set | None = None) -> list:
        """Durably-stored keys NO store outside ``exclude`` holds —
        the zero-loss audit the churn bench runs after each change."""
        exclude = exclude or set()
        held: set = set()
        everywhere: set = set()
        # dcflint: disable=guarded-by read-only audit sweep: .items()
        # snapshots under the GIL; an audit racing a join may count or
        # miss the newcomer's store, and either answer is a valid
        # point-in-time audit (the bench re-polls)
        for host_id, store in self._stores.items():
            try:
                keys = {k for k in store.digest()
                        if not k.startswith(_POOL_PREFIX)}
            except OSError:
                # fallback-ok: an unreadable store contributes to
                # NEITHER side — we cannot know what it held; counted
                self._c_store_unreachable.inc()
                continue
            everywhere |= keys
            if host_id not in exclude:
                held |= keys
        lost = sorted(everywhere - held)
        with self._state_lock:
            # Count each loss ONCE across repeated audits (a monitor
            # polling this must not inflate the counter); a key that
            # heals and is lost AGAIN is a new loss and counts again.
            fresh = [k for k in lost if k not in self._lost_counted]
            if fresh:
                self._c_lost.inc(len(fresh))
            self._lost_counted.intersection_update(lost)
            self._lost_counted.update(fresh)
        return lost

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "MembershipController":
        """Spawn the control worker (idempotent): evaluates the eject
        grace and finishes drains every ``poll_interval_s``."""
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._worker_loop, name="dcf-membership",
                daemon=True)
            self._worker.start()
        return self

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.pump()
            except Exception:  # fallback-ok: the control worker must
                # outlive any one round's failure (counted inside
                # pump's per-change containment where attributable)
                self._c_op_failures.inc()
            self._stop.wait(self.poll_interval_s)

    def close(self) -> None:
        self._stop.set()
        worker = self._worker
        if worker is not None and worker.is_alive() \
                and worker is not threading.current_thread():
            worker.join(5.0)
        self._worker = None

    def __repr__(self) -> str:
        return (f"MembershipController(ring={self._router.map.host_ids()},"
                f" epoch={self._router.ring_epoch}, "
                f"eject_grace_s={self.eject_grace_s}, "
                f"draining={sorted(self.draining())})")
