"""Micro-batch planning: coalesce ragged requests into padded device
batches, and scatter batch results back per request.

Pure host math — no backend, no clock, no device: a plan is a pure
function of the request sizes and the batch cap, so this layer is
exhaustively property-testable in isolation (tests/test_serve_batcher.py)
and the service above it stays thin.

Why pad to a power of two: every distinct staged batch shape costs one
XLA/Mosaic compile.  Ragged online traffic would otherwise compile a
fresh program per novel total; snapping totals to powers of two bounds
the compile universe to ``log2(max_batch)`` shapes, all warmed within the
first seconds of serving.  Pad rows are zero — a genuine evaluation of
x=0 whose output the scatter step simply never reads (same policy as the
backends' own 32-point lane padding).

Out-of-order completion is safe by construction: each request's output
rows are described by disjoint ``Span``s, so batches may complete in any
order (the double-buffered pipeline finishes batch N while N+1 is in
flight) and each span writes its slice into the request's own
preallocated output.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from dcf_tpu.errors import ShapeError

__all__ = ["Span", "BatchPlan", "next_pow2", "plan_batches",
           "ingest_points", "gather_batch", "scatter_batch"]


@dataclass(frozen=True)
class Span:
    """One contiguous run of points: request ``req``'s rows
    [req_off, req_off+length) live at batch rows
    [batch_off, batch_off+length)."""

    req: int
    req_off: int
    batch_off: int
    length: int


@dataclass(frozen=True)
class BatchPlan:
    """One device batch: ``spans`` cover rows [0, m); rows [m, padded_m)
    are zero padding (evaluated, never scattered)."""

    spans: tuple[Span, ...]
    m: int
    padded_m: int

    @property
    def occupancy(self) -> float:
        """Useful fraction of the padded batch (the occupancy metric)."""
        return self.m / self.padded_m if self.padded_m else 0.0


def next_pow2(m: int) -> int:
    """Smallest power of two >= m (>= 1)."""
    return 1 << max(m - 1, 0).bit_length()


def plan_batches(sizes: Sequence[int], max_batch: int) -> list[BatchPlan]:
    """FIFO-greedy coalescing of request sizes into batches of at most
    ``max_batch`` points, each padded up to the next power of two.

    Requests fill the current batch in submission order; a request that
    does not fit in the remaining space is SPLIT across batches (its
    spans reassemble it — occupancy beats keeping requests whole, and
    point order within a request is preserved either way).  ``max_batch``
    must itself be a power of two so padded batches never exceed it.
    """
    if max_batch < 1 or max_batch & (max_batch - 1):
        raise ShapeError(
            f"max_batch must be a power of two >= 1, got {max_batch}")
    for i, s in enumerate(sizes):
        if s < 1:
            raise ShapeError(f"request {i} has {s} points; requests are "
                             "non-empty by admission")
    plans: list[BatchPlan] = []
    spans: list[Span] = []
    fill = 0
    for req, size in enumerate(sizes):
        done = 0
        while done < size:
            take = min(size - done, max_batch - fill)
            spans.append(Span(req=req, req_off=done, batch_off=fill,
                              length=take))
            fill += take
            done += take
            if fill == max_batch:
                plans.append(BatchPlan(tuple(spans), fill, fill))
                spans, fill = [], 0
    if spans:
        plans.append(BatchPlan(tuple(spans), fill, next_pow2(fill)))
    return plans


def ingest_points(data, n_bytes: int, m: int | None = None) -> np.ndarray:
    """The ONE bytes-ingest entry feeding the batcher (ISSUE 12): wrap a
    buffer-protocol object holding ``m`` packed ``n_bytes``-wide points
    as the uint8 [m, n_bytes] array ``gather_batch`` reads spans from —
    ZERO copies and zero per-point Python objects (``np.frombuffer``
    aliases the caller's buffer; the one copy on the wire path is the
    socket read into that buffer, and the next is the span gather into
    the padded device batch).

    Both ingest paths route here: ``DcfService.submit`` hands the
    normalized ndarray's own buffer over, and the network edge
    (``serve.edge``) hands the received frame's payload ``memoryview``
    — so "what the batcher evaluates" has exactly one definition and
    the zero-copy claim is assertable at this seam.

    ``m=None`` derives the point count from the buffer size (must
    divide exactly).  The caller owns the buffer's lifetime: it must
    stay untouched until the request's batches have been gathered
    (the edge allocates one fresh buffer per frame for exactly this
    reason).
    """
    view = memoryview(data)
    if view.ndim != 1 or view.itemsize != 1:
        view = view.cast("B")  # flatten: C-contiguous bytes either way
    total = view.nbytes
    if n_bytes < 1:
        raise ShapeError(f"n_bytes must be >= 1, got {n_bytes}")
    if m is None:
        m, rem = divmod(total, n_bytes)
        if rem:
            raise ShapeError(
                f"payload of {total} bytes is not a whole number of "
                f"{n_bytes}-byte points ({rem} trailing bytes)")
    elif total != m * n_bytes:
        raise ShapeError(
            f"payload of {total} bytes != {m} points x {n_bytes} bytes")
    if m < 1:
        raise ShapeError("cannot ingest an empty request")
    return np.frombuffer(view, dtype=np.uint8).reshape(m, n_bytes)


def gather_batch(xs_list: Sequence[np.ndarray],
                 plan: BatchPlan, n_bytes: int) -> np.ndarray:
    """Assemble one padded device batch uint8 [padded_m, n_bytes] from
    the per-request point arrays (``xs_list[i]`` is request i's full
    uint8 [m_i, n_bytes]).  Pad rows stay zero."""
    out = np.zeros((plan.padded_m, n_bytes), dtype=np.uint8)
    for sp in plan.spans:
        out[sp.batch_off:sp.batch_off + sp.length] = \
            xs_list[sp.req][sp.req_off:sp.req_off + sp.length]
    return out


def scatter_batch(outs: Sequence[np.ndarray], plan: BatchPlan,
                  y: np.ndarray) -> None:
    """Scatter one completed batch result back into the per-request
    output buffers.

    ``y``: uint8 [K, padded_m(or m), lam] — the backend's bytes for this
    batch; ``outs[i]``: request i's preallocated uint8 [K, m_i, lam].
    Only span rows are read, so pad rows and completion order are
    irrelevant.
    """
    for sp in plan.spans:
        outs[sp.req][:, sp.req_off:sp.req_off + sp.length, :] = \
            y[:, sp.batch_off:sp.batch_off + sp.length, :]
