"""Demand-driven pod autoscaling: the capacity controller (ISSUE 16).

PRs 14–15 made ring membership react to *health*: a dead shard is
ejected, a returning one joins warm.  Nothing reacted to *load* — a
traffic surge latched brownout and shed BATCH until an operator added
a host by hand, and an idle pod burned hosts it did not need.  This
module closes the membership loop on demand: a ``CapacityController``
that watches the demand signals the repo already emits and sizes the
ring through the SAME epoch-fenced join/drain machinery, so
autoscaling inherits every membership fence instead of growing a
second reconfiguration path.

Signals (per shard, sampled off the health prober's PING/PONG round
trip — ``edge.LoadSample``, see ``serve.health``): queue points vs the
admission bound, the PR 6 brownout latch, and the cumulative
``serve_shed_total`` / ``edge_refused_total`` /
``keyfactory_pool_misses_total`` counters.  Each control tick the
controller aggregates the freshest samples across shards via the
metrics-rollup path (``serve.metrics.rollup_snapshots`` — the same
summation discipline the pod dashboards use), differences the
cumulative counters against the previous tick, and computes a typed
``CapacityVerdict``:

* **pressure** — the pod is demand-bound: the brownout fraction (shards
  in brownout / shards sampled) or the pooled queue fraction (summed
  points / summed bounds) crossed its threshold, or sheds / tenant
  refusals / key-factory pool misses accrued this tick;
* **idle** — the pod is over-provisioned: queue fraction under the idle
  threshold, zero brownout, zero new sheds/refusals/misses;
* **steady** — anything in between (including "nothing sampled yet":
  no evidence is never a scaling reason).

Hysteresis — the prober's fail-N/recover-M discipline lifted to
scaling decisions: scale-out only after ``scale_out_n`` CONSECUTIVE
pressure ticks, scale-in only after ``scale_in_m`` consecutive idle
ticks (idle evidence should have to work harder than pressure
evidence: shrinking too eagerly re-browns the pod), any other verdict
resets the streak.  On top of that sits a hard **cooldown**: after ANY
observed ring-epoch change — this controller's own commits AND health
ejects alike — no scaling change commits for ``cooldown_s``, and the
streaks reset (a membership change invalidates the evidence that
preceded it).  Oscillating load inside the hysteresis windows
therefore produces exactly ZERO ring churn — pinned by the flap tests
and the surge bench's oscillation leg.

Scale-out admits a host from the declared **standby pool** (ordered
``(ShardSpec, KeyStore | None)`` entries — ``serve_host --standby``
processes, provisioned but not in the ring) through
``MembershipController.join``: warm-before-admit, epoch-fenced.
Scale-in drains the LEAST-LOADED ring host (smallest sampled queue
points) through ``MembershipController.drain`` — durable key
migration, deferred forget — and returns it to the back of the
standby pool, store attached.  Safety rails, each a counted skip
(``capacity_skips_total{reason=...}``): never below ``min_hosts``
(reason ``min_hosts``), never concurrent with an in-flight health
eject (``eject_inflight`` — ``MembershipController.eject_in_flight``),
never inside the cooldown (``cooldown``), never past ``max_hosts``
(``max_hosts``), never without a standby host (``no_standby``) or a
load sample to pick a drain victim by (``no_sample``).  The automatic
loop only ever counts; the explicit ``scale_out()`` /
``scale_in(host_id)`` operator verbs raise typed
(``StandbyExhaustedError`` on an empty pool).

Fault seam: ``capacity.decide`` fires once per tick with the computed
verdict.  A handler raising ``ForcedVerdict(kind)`` FORCES that kind
for the tick (how the surge bench's oscillation leg scripts a load
walk without timing games); any other raise FREEZES the tick — no
streak advance, no scaling, counted ``reason=frozen`` — the
operator's emergency brake.

Driving modes mirror ``HealthProber`` / ``MembershipController``:
``start()`` spawns a worker ticking every ``interval_s``; ``pump()``
runs one tick inline on the injectable clock (the deterministic
test mode) and returns the verdict it acted on.  Every committed
change is a typed ``CapacityEvent`` plus the ``capacity_*`` metric
series (see ``serve.metrics``).

Secret hygiene: this module handles load arithmetic and host names
only — key material stays inside the membership/edge calls it
delegates to.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace

from dcf_tpu.errors import StandbyExhaustedError
from dcf_tpu.serve.metrics import labeled, rollup_snapshots
from dcf_tpu.serve.shardmap import ShardSpec
from dcf_tpu.testing.faults import fire

__all__ = ["PRESSURE", "IDLE", "STEADY", "CapacityVerdict",
           "CapacityEvent", "ForcedVerdict", "CapacityController"]

PRESSURE = "pressure"
IDLE = "idle"
STEADY = "steady"

#: The typed verdict vocabulary (severity order, like HEALTH_CODES).
VERDICT_CODES = {IDLE: -1, STEADY: 0, PRESSURE: 1}


@dataclass(frozen=True)
class CapacityVerdict:
    """One control tick's aggregated pressure reading.  ``kind`` is
    ``pressure`` / ``idle`` / ``steady``; ``sampled`` how many ring
    hosts contributed a ``LoadSample`` this tick; the fractions and
    per-tick deltas are the aggregated signals the kind was computed
    from (deltas are 0 on a host's FIRST sample — pre-existing totals
    are history, not fresh demand); ``at`` the injectable-clock
    time."""

    kind: str
    sampled: int
    ring_size: int
    brownout_fraction: float
    queue_fraction: float
    shed_delta: int
    refusal_delta: int
    pool_miss_delta: int
    at: float


@dataclass(frozen=True)
class CapacityEvent:
    """One committed scaling change: ``kind`` is ``scale-out`` /
    ``scale-in``, ``epoch`` the ring epoch it committed under, ``at``
    the injectable-clock time."""

    kind: str
    host_id: str
    epoch: int
    at: float


class ForcedVerdict(Exception):
    """Control-flow exception for the ``capacity.decide`` seam: a
    handler raising this forces the tick's verdict to ``kind`` (the
    scripted-load-walk tool; see the module docstring).  Any OTHER
    exception from the seam freezes the tick instead."""

    def __init__(self, kind: str):
        if kind not in VERDICT_CODES:
            # api-edge: seam-usage contract (a typo'd kind must fail
            # the test arming it, not silently freeze every tick)
            raise ValueError(
                f"verdict kind must be one of "
                f"{sorted(VERDICT_CODES)}, got {kind!r}")
        super().__init__(kind)
        self.kind = kind


class CapacityController:
    """Load-signal capacity controller over one ``DcfRouter`` +
    ``MembershipController`` pair (see the module docstring).

    ``standby``: the declared standby pool — an ordered iterable of
    ``ShardSpec`` or ``(ShardSpec, KeyStore)`` entries, consumed
    front-first on scale-out; drained hosts return to the back.
    ``scale_out_n`` / ``scale_in_m``: the consecutive-tick hysteresis.
    ``cooldown_s``: the hard floor between ANY two membership changes
    this controller observes (its own and the health plane's).
    ``min_hosts`` defaults to the membership controller's floor;
    ``max_hosts`` (None = unbounded) caps scale-out.  Thresholds:
    ``brownout_pressure_fraction`` / ``queue_pressure_fraction`` flag
    pressure, ``queue_idle_fraction`` gates idle; the per-tick
    shed/refusal/pool-miss deltas flag pressure at >= 1.
    ``clock``: the injectable clock (defaults to the router's)."""

    def __init__(self, router, membership, *, standby=(),
                 interval_s: float = 1.0, scale_out_n: int = 3,
                 scale_in_m: int = 6, cooldown_s: float = 30.0,
                 min_hosts: int | None = None,
                 max_hosts: int | None = None,
                 brownout_pressure_fraction: float = 0.5,
                 queue_pressure_fraction: float = 0.75,
                 queue_idle_fraction: float = 0.05,
                 clock=None, max_events: int = 256):
        if interval_s <= 0:
            # api-edge: controller config contract
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        if scale_out_n < 1 or scale_in_m < 1:
            # api-edge: controller config contract — 0 would scale on
            # a single tick's noise, i.e. flap on every reading
            raise ValueError(
                f"scale_out_n/scale_in_m must be >= 1, got "
                f"{scale_out_n}/{scale_in_m}")
        if cooldown_s < 0:
            # api-edge: controller config contract
            raise ValueError(
                f"cooldown_s must be >= 0, got {cooldown_s}")
        if not 0 < brownout_pressure_fraction <= 1 \
                or not 0 < queue_pressure_fraction <= 1:
            # api-edge: controller config contract
            raise ValueError(
                f"pressure fractions must be in (0, 1], got "
                f"brownout={brownout_pressure_fraction}/"
                f"queue={queue_pressure_fraction}")
        if not 0 <= queue_idle_fraction < queue_pressure_fraction:
            # api-edge: controller config contract — an idle threshold
            # at or above the pressure threshold makes one queue
            # reading both verdicts at once
            raise ValueError(
                f"queue_idle_fraction must be in [0, "
                f"queue_pressure_fraction), got {queue_idle_fraction}"
                f" vs {queue_pressure_fraction}")
        self._router = router
        self._membership = membership
        self.interval_s = float(interval_s)
        self.scale_out_n = int(scale_out_n)
        self.scale_in_m = int(scale_in_m)
        self.cooldown_s = float(cooldown_s)
        self.min_hosts = int(min_hosts if min_hosts is not None
                             else membership.min_hosts)
        if self.min_hosts < 1:
            # api-edge: controller config contract
            raise ValueError(
                f"min_hosts must be >= 1, got {self.min_hosts}")
        self.max_hosts = None if max_hosts is None else int(max_hosts)
        if self.max_hosts is not None \
                and self.max_hosts < self.min_hosts:
            # api-edge: controller config contract
            raise ValueError(
                f"max_hosts must be >= min_hosts, got "
                f"{self.max_hosts} < {self.min_hosts}")
        self.brownout_pressure_fraction = float(
            brownout_pressure_fraction)
        self.queue_pressure_fraction = float(queue_pressure_fraction)
        self.queue_idle_fraction = float(queue_idle_fraction)
        self._clock = clock if clock is not None else router._clock
        self._max_events = int(max_events)
        self._lock = threading.Lock()       # standby/event state
        self._pump_lock = threading.Lock()  # one control tick at a time
        # guarded-by: _lock
        self._standby: list = [self._standby_entry(e) for e in standby]
        # Tick-cursor state: written only by the (serialized) control
        # tick, so the pump lock IS its guard.
        # guarded-by: _pump_lock
        self._prev_totals: dict = {}  # host -> (shed, refused, misses)
        # guarded-by: _pump_lock
        self._last_loads: dict = {}
        # guarded-by: _pump_lock
        self._pressure_streak = 0
        # guarded-by: _pump_lock
        self._idle_streak = 0
        # guarded-by: _pump_lock
        self._last_epoch = router.ring_epoch
        # guarded-by: _pump_lock
        self._cooldown_until = 0.0
        self.last_verdict: CapacityVerdict | None = None
        # guarded-by: _lock
        self._events: list[CapacityEvent] = []
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        m = router.metrics
        self._metrics = m
        self._c_ticks = m.counter("capacity_ticks_total")
        self._c_pressure = m.counter("capacity_pressure_ticks_total")
        self._c_idle = m.counter("capacity_idle_ticks_total")
        self._c_out = m.counter("capacity_scale_out_total")
        self._c_in = m.counter("capacity_scale_in_total")
        self._c_failures = m.counter("capacity_scale_failures_total")
        self._c_forced = m.counter("capacity_forced_verdicts_total")
        self._g_standby = m.gauge("capacity_standby_hosts")
        self._g_pressure_streak = m.gauge("capacity_pressure_streak")
        self._g_idle_streak = m.gauge("capacity_idle_streak")
        self._g_queue_fraction = m.gauge("capacity_queue_fraction")
        self._g_brownout_fraction = m.gauge(
            "capacity_brownout_fraction")
        self._g_standby.set(len(self._standby))

    @staticmethod
    def _standby_entry(entry) -> tuple:
        if isinstance(entry, ShardSpec):
            return entry, None
        spec, store = entry
        if not isinstance(spec, ShardSpec):
            # api-edge: standby-pool declaration contract
            raise ValueError(
                f"standby entries must be ShardSpec or (ShardSpec, "
                f"store), got {type(spec).__name__}")
        return spec, store

    # -- observability ------------------------------------------------

    def events(self) -> list:
        """Drain the committed scaling events observed so far
        (bounded, like the sibling controllers — the ``capacity_*``
        metrics are the durable record)."""
        with self._lock:
            out, self._events = self._events, []
            return out

    def standby(self) -> list:
        """Host ids currently waiting in the standby pool, in
        admission order."""
        with self._lock:
            return [spec.host_id for spec, _store in self._standby]

    def add_standby(self, spec: ShardSpec, store=None) -> None:
        """Declare one more standby host (appended — the pool is
        consumed front-first)."""
        entry = self._standby_entry((spec, store))
        with self._lock:
            self._standby.append(entry)
            self._g_standby.set(len(self._standby))

    def _record(self, kind: str, host_id: str,
                epoch: int) -> CapacityEvent:
        ev = CapacityEvent(kind, host_id, int(epoch), self._clock())
        with self._lock:
            self._events.append(ev)
            del self._events[:-self._max_events]
        return ev

    def _skip(self, reason: str) -> None:
        self._metrics.counter(labeled(
            "capacity_skips_total", reason=reason)).inc()

    # -- the control tick ---------------------------------------------

    # holds-lock: _pump_lock
    def _assess(self, now: float) -> CapacityVerdict:
        """Aggregate the freshest per-shard samples into one verdict
        (the metrics-rollup path: per-shard mini-snapshots summed by
        ``rollup_snapshots``, exactly like the pod dashboard view)."""
        ring_ids = set(self._router.map.host_ids())
        loads = {h: s for h, s in self._router.health.loads().items()
                 if h in ring_ids}
        self._last_loads = loads
        snaps = []
        deltas = {"shed": 0, "refused": 0, "misses": 0}
        fresh_totals: dict = {}
        for host_id, s in sorted(loads.items()):
            if s is None:
                continue  # answered, but no load surface
            snaps.append({
                "serve_queue_points": s.queue_points,
                "serve_queue_limit": s.queue_limit,
                "serve_brownout": 1 if s.brownout else 0,
                "serve_shed_total": s.shed_total,
                "edge_refused_total": s.refusals_total,
                "keyfactory_pool_misses_total": s.pool_misses,
            })
            totals = (s.shed_total, s.refusals_total, s.pool_misses)
            prev = self._prev_totals.get(host_id)
            if prev is not None:
                # max(0, ...): a restarted shard's counters reset —
                # a negative "delta" is a restart, not negative demand
                deltas["shed"] += max(totals[0] - prev[0], 0)
                deltas["refused"] += max(totals[1] - prev[1], 0)
                deltas["misses"] += max(totals[2] - prev[2], 0)
            fresh_totals[host_id] = totals
        self._prev_totals = fresh_totals  # hosts that left fall away
        sampled = len(snaps)
        agg = rollup_snapshots(snaps) if snaps else {}
        qp = agg.get("serve_queue_points", 0)
        ql = agg.get("serve_queue_limit", 0)
        queue_fraction = (qp / ql) if ql else 0.0
        brownout_fraction = (agg.get("serve_brownout", 0) / sampled
                             if sampled else 0.0)
        if sampled == 0:
            kind = STEADY  # no evidence is never a scaling reason
        elif (brownout_fraction >= self.brownout_pressure_fraction
              or queue_fraction >= self.queue_pressure_fraction
              or deltas["shed"] >= 1 or deltas["refused"] >= 1
              or deltas["misses"] >= 1):
            kind = PRESSURE
        elif queue_fraction <= self.queue_idle_fraction \
                and brownout_fraction == 0:
            kind = IDLE
        else:
            kind = STEADY
        return CapacityVerdict(
            kind=kind, sampled=sampled, ring_size=len(ring_ids),
            brownout_fraction=brownout_fraction,
            queue_fraction=queue_fraction,
            shed_delta=deltas["shed"],
            refusal_delta=deltas["refused"],
            pool_miss_delta=deltas["misses"], at=now)

    def pump(self) -> CapacityVerdict | None:
        """One control tick inline (the deterministic driving mode):
        aggregate, decide, and — hysteresis and rails permitting —
        scale.  Returns the verdict acted on (post-seam), or None for
        a frozen tick."""
        with self._pump_lock:
            now = self._clock()
            self._c_ticks.inc()
            verdict = self._assess(now)
            try:
                fire("capacity.decide", verdict.kind, verdict)
            except ForcedVerdict as f:
                self._c_forced.inc()
                verdict = replace(verdict, kind=f.kind)
            except Exception:  # fallback-ok: ANY other raise from the
                # seam freezes the tick — no streak advance, no
                # scaling, counted; the operator's emergency brake
                self._skip("frozen")
                return None
            self.last_verdict = verdict
            self._g_queue_fraction.set(
                round(verdict.queue_fraction, 9))
            self._g_brownout_fraction.set(
                round(verdict.brownout_fraction, 9))
            # The epoch-observed cooldown: ANY membership commit since
            # the last tick — ours or the health plane's — restarts
            # the clock AND resets the streaks (a ring change
            # invalidates the evidence gathered against the old ring).
            epoch = self._router.ring_epoch
            if epoch != self._last_epoch:
                self._last_epoch = epoch
                self._cooldown_until = now + self.cooldown_s
                self._pressure_streak = 0
                self._idle_streak = 0
            if verdict.kind == PRESSURE:
                self._c_pressure.inc()
                self._pressure_streak += 1
                self._idle_streak = 0
            elif verdict.kind == IDLE:
                self._c_idle.inc()
                self._idle_streak += 1
                self._pressure_streak = 0
            else:
                self._pressure_streak = 0
                self._idle_streak = 0
            self._g_pressure_streak.set(self._pressure_streak)
            self._g_idle_streak.set(self._idle_streak)
            if self._pressure_streak >= self.scale_out_n:
                self._maybe_scale_out(now)
            elif self._idle_streak >= self.scale_in_m:
                self._maybe_scale_in(now)
            return verdict

    # -- scaling ------------------------------------------------------

    # holds-lock: _pump_lock
    def _rails(self, now: float) -> str | None:
        """The shared rails, in announcement order; returns the
        counted skip reason or None (clear to scale)."""
        if now < self._cooldown_until:
            return "cooldown"
        if self._membership.eject_in_flight():
            return "eject_inflight"
        return None

    # holds-lock: _pump_lock
    def _maybe_scale_out(self, now: float) -> None:
        reason = self._rails(now)
        if reason is None and self.max_hosts is not None \
                and len(self._router.map) >= self.max_hosts:
            reason = "max_hosts"
        entry = None
        if reason is None:
            # Emptiness check and pop under ONE lock acquisition
            # (ISSUE 17 guarded-by sweep): the old unlocked
            # `not self._standby` probe could race a concurrent pool
            # mutation between check and pop — the claim must be
            # atomic with the decision that the pool has something to
            # claim.
            with self._lock:
                if self._standby:
                    entry = self._standby.pop(0)
                    self._g_standby.set(len(self._standby))
                else:
                    reason = "no_standby"
        if reason is not None:
            self._skip(reason)
            return
        spec, store = entry
        try:
            ev = self._membership.join(spec, store=store)
        except Exception:  # fallback-ok: a failed join (the standby
            # host died, a warm source failed) was counted by the
            # membership layer; the host returns to the FRONT of the
            # pool and the streak retries on a later tick
            self._c_failures.inc()
            with self._lock:
                self._standby.insert(0, (spec, store))
                self._g_standby.set(len(self._standby))
            return
        self._after_change(now)
        self._c_out.inc()
        self._record("scale-out", spec.host_id, ev.epoch)

    # holds-lock: _pump_lock
    def _maybe_scale_in(self, now: float) -> None:
        reason = self._rails(now)
        if reason is None \
                and len(self._router.map) <= self.min_hosts:
            reason = "min_hosts"
        victim = None
        if reason is None:
            sampled = {h: s for h, s in self._last_loads.items()
                       if s is not None and h in self._router.map}
            if not sampled:
                reason = "no_sample"
            else:
                victim = min(sorted(sampled),
                             key=lambda h: sampled[h].queue_points)
        if reason is not None:
            self._skip(reason)
            return
        spec = self._router.map.get(victim)
        store = self._membership.store_for(victim)
        try:
            ev = self._membership.drain(victim)
        except Exception:  # fallback-ok: a failed drain (a migration
            # source died) was counted by the membership layer; the
            # host stays a full member and the streak retries later
            self._c_failures.inc()
            return
        self._after_change(now)
        self._c_in.inc()
        if spec is not None:
            # Back of the pool: a just-drained host is the LAST one a
            # future surge should re-admit (coldest caches).
            with self._lock:
                self._standby.append((spec, store))
                self._g_standby.set(len(self._standby))
        self._record("scale-in", victim, ev.epoch)

    # holds-lock: _pump_lock
    def _after_change(self, now: float) -> None:
        """Bookkeeping after OUR OWN committed change: adopt the fresh
        epoch (so the next tick's observation does not double-restart
        the cooldown), start the cooldown, reset the streaks."""
        self._last_epoch = self._router.ring_epoch
        self._cooldown_until = now + self.cooldown_s
        self._pressure_streak = 0
        self._idle_streak = 0
        self._g_pressure_streak.set(0)
        self._g_idle_streak.set(0)

    # -- operator verbs -----------------------------------------------

    def scale_out(self) -> CapacityEvent:
        """Admit the next standby host NOW (the operator's verb):
        bypasses the hysteresis and the cooldown but never the
        membership fences.  Raises typed ``StandbyExhaustedError`` on
        an empty pool — an operator asking for capacity that does not
        exist must not get a silent no-op."""
        with self._pump_lock:
            with self._lock:
                if not self._standby:
                    raise StandbyExhaustedError(
                        "standby pool is empty: no host to admit "
                        "(declare more with add_standby, or drain "
                        "elsewhere first)")
                spec, store = self._standby.pop(0)
                self._g_standby.set(len(self._standby))
            try:
                ev = self._membership.join(spec, store=store)
            except Exception:  # fallback-ok: count + restore the pool,
                # then re-raise — the operator called, the operator
                # sees the join's own typed failure
                self._c_failures.inc()
                with self._lock:
                    self._standby.insert(0, (spec, store))
                    self._g_standby.set(len(self._standby))
                raise
            self._after_change(self._clock())
            self._c_out.inc()
            return self._record("scale-out", spec.host_id, ev.epoch)

    def scale_in(self, host_id: str) -> CapacityEvent:
        """Drain ``host_id`` NOW and return it to the standby pool
        (the operator's verb): bypasses hysteresis and cooldown, never
        the membership fences (``drain`` refuses the last host; the
        ``min_hosts`` floor is the AUTOMATIC loop's rail — a planned
        decommission is the operator's call, same as membership)."""
        with self._pump_lock:
            spec = self._router.map.get(host_id)
            store = self._membership.store_for(host_id)
            ev = self._membership.drain(host_id)
            self._after_change(self._clock())
            self._c_in.inc()
            if spec is not None:
                with self._lock:
                    self._standby.append((spec, store))
                    self._g_standby.set(len(self._standby))
            return self._record("scale-in", host_id, ev.epoch)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "CapacityController":
        """Spawn the control worker (idempotent): one tick every
        ``interval_s``."""
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._worker_loop, name="dcf-capacity",
                daemon=True)
            self._worker.start()
        return self

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.pump()
            except Exception:  # fallback-ok: the control worker must
                # outlive any one tick's failure (scaling failures are
                # counted inside pump's per-change containment)
                self._c_failures.inc()
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        worker = self._worker
        if worker is not None and worker.is_alive() \
                and worker is not threading.current_thread():
            worker.join(5.0)
        self._worker = None

    def __repr__(self) -> str:
        return (f"CapacityController(ring={self._router.map.host_ids()},"
                f" standby={self.standby()}, "
                f"scale_out_n={self.scale_out_n}, "
                f"scale_in_m={self.scale_in_m}, "
                f"cooldown_s={self.cooldown_s})")
