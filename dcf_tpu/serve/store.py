"""Durable key store: DCFK frames on disk + a CRC'd manifest (ISSUE 8).

DCF keys are per-session cryptographic assets whose generation is the
expensive offline phase (Boyle et al.; ROADMAP item 3 flags keygen as
the unbenchmarked production bottleneck) — yet a ``DcfService`` restart
used to forget every registered bundle, forcing a full regen.  This
module is the process-lifecycle half of the resilience story: keys
registered ``durable=True`` survive a crash, and
``KeyRegistry.restore(store)`` brings them back with their generations
intact, so a restarted host serves the same key shard it died with and
re-keygens nothing.

On-disk layout (one directory, created ``0o700``)::

    <root>/
      MANIFEST.dcfm            the CRC'd manifest (layout below)
      <digest>-g<gen>.dcfk     one DCFK v2/v3 frame per durable key
      <...>.quarantined-<n>    frames set aside by the quarantine path

* **Frames** are the existing wire formats verbatim — ``KeyBundle``
  v2 for plain keys, ``ProtocolBundle`` v3 for protocol keys — so the
  store inherits their CRC32 trailers and strict field-naming decode;
  there is exactly one codec per format in the repo.  The filename
  carries a digest of the key id plus the GENERATION, so a hot-swap
  writes a NEW file and flips the manifest afterwards: no crash window
  can pair new key bytes with an old generation (the aliasing the PR 5
  snapshot guard exists to prevent, extended across process death).
* **Every publish is write-fsync-rename**: the payload goes to a temp
  file in the same directory (``os.open`` with ``0o600`` — key frames
  on disk are key material), is flushed and fsynced, and only then
  atomically renamed over the destination; the directory is fsynced
  after.  A crash at ANY point leaves either the old state or the new
  state, never a torn visible file.  ``put_many``/``delete_many``
  (ISSUE 11) batch the manifest side: every frame in a refill batch is
  still published individually, but ONE manifest flip makes the whole
  batch visible — the key factory's amortization of the fsync cost
  without giving up the crash guarantee (a kill mid-batch leaves the
  previous manifest and some orphan frames, never a torn pool).  The ``store.write`` /
  ``store.manifest`` fault seams fire between fsync and rename
  (``testing.faults``: raise = crash pre-publish, ``torn_write`` =
  a partial write made durable for the quarantine path to find).
* **The manifest** maps ``key_id -> (file, generation, proto flag,
  party count)`` and is itself framed: magic ``DCFM``, version, exact
  body length, JSON body (sorted keys — deterministic bytes for a
  given state), CRC32 trailer.  Any mutation dies with a typed
  ``KeyFormatError`` naming the field — a store whose index cannot be
  trusted must fail loudly, not serve a guess.
* **Quarantine**: a frame that fails validation at read time is set
  ASIDE, not skipped — the file is renamed ``.quarantined-<n>``, its
  manifest entry dropped, ``serve_store_quarantined_total`` bumped,
  and ``KeyQuarantinedError`` raised (cause-chained to the underlying
  ``KeyFormatError``).  ``KeyRegistry.restore`` catches it PER KEY:
  one damaged frame is never silently skipped and never fatal to the
  other keys.

Thread safety: one lock per store serializes every mutation (the
write-through path runs on whatever thread calls ``register_key``).
Determinism: no clocks, no RNG — file contents are a pure function of
the store's logical state (the dcflint determinism pass holds this
module to that).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field

from dcf_tpu.errors import (
    BackendUnavailableError,
    KeyFormatError,
    KeyQuarantinedError,
    ShapeError,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.serve.metrics import Metrics
from dcf_tpu.testing.faults import fire

__all__ = ["KeyStore", "RestoreReport", "MANIFEST_NAME"]

MANIFEST_NAME = "MANIFEST.dcfm"
_MANIFEST_MAGIC = b"DCFM"
_MANIFEST_VERSION = 1
_MANIFEST_HEADER = "<HI"  # version, body length (after the 4-byte magic)
_MANIFEST_HEADER_SIZE = 4 + struct.calcsize(_MANIFEST_HEADER)
_CRC_SIZE = 4
_FRAME_SUFFIX = ".dcfk"


@dataclass
class RestoreReport:
    """What a warm restart brought back: ``restored`` maps key_id to
    its preserved generation; ``quarantined`` maps key_id to the typed
    failure message of the frame that was set aside; ``repooled``
    (ISSUE 11) maps ``~pool/...`` frame ids to their preserved
    generations — un-claimed key-factory supply routed back into its
    pools by ``DcfService.restore_keys`` instead of the serving
    registry."""

    restored: dict = field(default_factory=dict)
    quarantined: dict = field(default_factory=dict)
    repooled: dict = field(default_factory=dict)

    def __repr__(self) -> str:  # names and counts only, never contents
        return (f"RestoreReport(restored={sorted(self.restored)}, "
                f"quarantined={sorted(self.quarantined)}, "
                f"repooled={sorted(self.repooled)})")


def _frame_name(key_id: str, generation: int) -> str:
    """Deterministic frame filename: a digest of the key id (ids are
    caller-chosen and may contain path separators) plus the generation
    — a hot-swap lands in a NEW file, so no crash window can pair new
    frame bytes with a stale manifest generation."""
    digest = hashlib.sha256(key_id.encode("utf-8")).hexdigest()[:16]
    return f"{digest}-g{int(generation)}{_FRAME_SUFFIX}"


class KeyStore:
    """Durable DCFK frame store under one directory (module docstring).

    ``put``/``delete`` are the write-through surface the service uses;
    ``load`` is the strict read (quarantines on corruption);
    ``key_ids``/``generation_of`` read the manifest.  All operations
    re-read the manifest from disk — the file is the source of truth,
    so two processes taking turns (crash, restart) always see the last
    published state.
    """

    def __init__(self, root: str, *, metrics: Metrics | None = None):
        self.root = str(root)
        self._lock = threading.Lock()
        self._metrics = metrics if metrics is not None else Metrics()
        os.makedirs(self.root, mode=0o700, exist_ok=True)
        m = self._metrics
        self._c_writes = m.counter("serve_store_writes_total")
        self._c_deletes = m.counter("serve_store_deletes_total")
        self._c_quarantined = m.counter("serve_store_quarantined_total")
        self._g_keys = m.gauge("serve_store_keys")
        # A pre-existing store's key count is visible from the first
        # snapshot, not only after the first mutation.
        with self._lock:
            try:
                self._g_keys.set(len(self._read_manifest()))
            except KeyFormatError:
                pass  # surfaced typed on the first real read

    def __repr__(self) -> str:
        return f"KeyStore(root={self.root!r})"

    # -- atomic publish -----------------------------------------------------

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.root, os.O_RDONLY)
        except OSError:
            return  # platforms without directory fds: rename still atomic
        try:
            os.fsync(fd)
        except OSError:
            pass  # directory fsync unsupported: best effort
        finally:
            os.close(fd)

    def _publish(self, name: str, data: bytes, seam: str,
                 key_id: str) -> None:
        """Write-fsync-rename ``data`` into ``<root>/<name>``.  The
        temp file is created ``0o600`` (frames are key material) in the
        SAME directory so the rename is atomic on every filesystem."""
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        # O_TRUNC, not O_EXCL: a temp file a previous crash left behind
        # must not wedge every later publish of the same name.
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        fire(seam, key_id, tmp)
        os.replace(tmp, path)
        self._fsync_dir()

    # -- manifest codec -----------------------------------------------------

    def _manifest_bytes(self, entries: dict) -> bytes:
        body = json.dumps({"keys": entries}, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        head = _MANIFEST_MAGIC + struct.pack(
            _MANIFEST_HEADER, _MANIFEST_VERSION, len(body))
        return head + body + struct.pack("<I", zlib.crc32(head + body))

    def _read_manifest(self) -> dict:
        """Strict manifest decode -> ``{key_id: entry}``; a missing
        manifest is an empty store, anything malformed raises
        ``KeyFormatError`` naming the offending field (the index of a
        key store must be trusted or rejected, never guessed at)."""
        path = os.path.join(self.root, MANIFEST_NAME)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except FileNotFoundError:
            return {}
        if len(data) < _MANIFEST_HEADER_SIZE + _CRC_SIZE:
            raise KeyFormatError(
                f"truncated manifest: {len(data)} bytes, the DCFM "
                f"header + CRC need {_MANIFEST_HEADER_SIZE + _CRC_SIZE}")
        if data[:4] != _MANIFEST_MAGIC:
            raise KeyFormatError(
                f"bad manifest magic: expected {_MANIFEST_MAGIC!r}, "
                f"got {bytes(data[:4])!r}")
        version, body_len = struct.unpack_from(_MANIFEST_HEADER, data, 4)
        if version != _MANIFEST_VERSION:
            raise KeyFormatError(
                f"unsupported manifest version {version} (this reader "
                f"handles {_MANIFEST_VERSION})")
        want = _MANIFEST_HEADER_SIZE + body_len + _CRC_SIZE
        if len(data) != want:
            raise KeyFormatError(
                f"manifest size mismatch: header claims a {body_len}-"
                f"byte body ({want} total), frame is {len(data)} bytes")
        payload_end = len(data) - _CRC_SIZE
        (crc_stored,) = struct.unpack_from("<I", data, payload_end)
        crc_actual = zlib.crc32(data[:payload_end])
        if crc_stored != crc_actual:
            raise KeyFormatError(
                f"manifest crc32 mismatch: trailer records "
                f"{crc_stored:#010x}, frame hashes to {crc_actual:#010x}")
        try:
            doc = json.loads(data[_MANIFEST_HEADER_SIZE:payload_end])
        except ValueError as e:
            raise KeyFormatError(
                f"manifest body is not valid JSON ({e})") from e
        if not isinstance(doc, dict) \
                or not isinstance(doc.get("keys"), dict):
            raise KeyFormatError(
                "manifest body must be an object with a 'keys' map")
        entries = doc["keys"]
        for key_id, ent in entries.items():
            self._check_entry(key_id, ent)
        return entries

    @staticmethod
    def _check_entry(key_id, ent) -> None:
        if not isinstance(key_id, str) or not key_id:
            raise KeyFormatError(
                f"manifest key id must be a non-empty string, "
                f"got {key_id!r}")
        if not isinstance(ent, dict):
            raise KeyFormatError(
                f"manifest entry for {key_id!r} must be an object")
        fname = ent.get("file")
        if not isinstance(fname, str) \
                or fname != os.path.basename(fname) \
                or not fname.endswith(_FRAME_SUFFIX):
            # A path-traversing or alien filename in a tampered
            # manifest must die here, not open an arbitrary path.
            raise KeyFormatError(
                f"manifest entry for {key_id!r} has a bad 'file' field: "
                f"{fname!r} (want a bare *{_FRAME_SUFFIX} name)")
        gen = ent.get("generation")
        if not isinstance(gen, int) or isinstance(gen, bool) or gen < 0:
            raise KeyFormatError(
                f"manifest entry for {key_id!r} has a bad 'generation' "
                f"field: {gen!r} (want an int >= 0)")
        if not isinstance(ent.get("proto"), bool):
            raise KeyFormatError(
                f"manifest entry for {key_id!r} has a bad 'proto' "
                f"field: {ent.get('proto')!r} (want a bool)")
        if ent.get("parties") not in (1, 2):
            raise KeyFormatError(
                f"manifest entry for {key_id!r} has a bad 'parties' "
                f"field: {ent.get('parties')!r} (want 1 or 2)")

    def _write_manifest(self, entries: dict) -> None:
        self._publish(MANIFEST_NAME, self._manifest_bytes(entries),
                      "store.manifest", "")
        self._g_keys.set(len(entries))

    # -- the write-through surface ------------------------------------------

    def put(self, key_id: str, bundle: KeyBundle, protocol=None,
            generation: int = 0, drop=()) -> None:
        """Persist ``key_id``'s frame durably (frame first, manifest
        second — a crash between the two leaves the previous manifest
        pointing at the previous file: consistent old state, one
        orphan frame for ``sweep_orphans``).  ``protocol``: the
        ``ProtocolBundle`` wrapper when the key is a protocol key (the
        v3 frame then carries the combine masks; ``bundle`` must be
        its inner ``KeyBundle``).  ``generation``: the registry
        generation the frame is published under — restore hands it
        back verbatim.

        ``drop`` (ISSUE 11): key ids whose entries are removed in the
        SAME manifest flip that publishes this one — the durable pool
        CLAIM path folds the spent ``~pool/...`` frame's delete into
        the session key's publish, so no crash window exists in which
        both the claimed pool frame and its durable session copy are
        manifest-visible (restoring both would hand the same key
        material to a second session — cross-session reuse, not a
        hygiene cost).  Unknown ids are ignored."""
        if bundle.s0s.shape[1] != 2:
            raise ShapeError(
                f"put({key_id!r}) wants the full two-party bundle — a "
                "restored service serves both parties")
        if protocol is not None and protocol.keys is not bundle:
            raise ShapeError(
                f"put({key_id!r}): protocol.keys is not the bundle "
                "being persisted — the frame would desync from the "
                "registry entry")
        if not key_id:
            # api-edge: store naming contract at the serve edge
            raise ValueError("key_id must be a non-empty string")
        payload = (protocol.to_bytes() if protocol is not None
                   else bundle.to_bytes())
        # A self-describing protocol frame (DpfBundle carries
        # WIRE_PROTO) is flagged proto in the manifest even without a
        # wrapper, so load() routes it through the proto dispatcher.
        is_proto = (protocol is not None
                    or getattr(bundle, "WIRE_PROTO", 0) != 0)
        fname = _frame_name(key_id, generation)
        with self._lock:
            entries = self._read_manifest()
            prev = entries.get(key_id)
            if prev is not None and prev["generation"] > generation:
                # A stale write-through: two concurrent durable
                # hot-swaps of the same key serialize on this lock in
                # arbitrary order, and persisting the OLDER generation
                # last would silently roll the key back at the next
                # restore.  Generations are the registry's total order
                # per key — the newest durable publish wins, always.
                return
            dropped = [entries.pop(d) for d in dict.fromkeys(drop)
                       if d != key_id and d in entries]
            self._publish(fname, payload, "store.write", key_id)
            entries[key_id] = {
                "file": fname,
                "generation": int(generation),
                "proto": is_proto,
                "parties": 2,
            }
            self._write_manifest(entries)
            self._c_writes.inc()
            if prev is not None and prev["file"] != fname:
                self._unlink_quiet(prev["file"])
            for ent in dropped:
                if ent["file"] != fname:
                    self._unlink_quiet(ent["file"])
                    self._c_deletes.inc()

    def put_many(self, items) -> int:
        """Batched durable publish (ISSUE 11, the key-factory refill
        path): persist every ``(key_id, bundle, protocol, generation)``
        in ``items`` with ONE manifest flip — each frame is still
        written write-fsync-rename individually (the ``store.write``
        seam fires per frame), but the batch becomes visible atomically
        when the single manifest publish renames into place.  A crash
        anywhere between the first frame write and the manifest flip
        leaves the PREVIOUS manifest intact: old state, a few orphan
        frames for ``sweep_orphans`` — never a torn pool.  Per-key
        semantics match ``put`` exactly (two-party contract, protocol
        desync check, the monotonic-generation guard: a stale item is
        skipped, not rolled back).  Returns the number of keys
        actually published (stale items excluded)."""
        staged = []
        for key_id, bundle, protocol, generation in items:
            if bundle.s0s.shape[1] != 2:
                raise ShapeError(
                    f"put_many({key_id!r}) wants the full two-party "
                    "bundle — a restored service serves both parties")
            if protocol is not None and protocol.keys is not bundle:
                raise ShapeError(
                    f"put_many({key_id!r}): protocol.keys is not the "
                    "bundle being persisted — the frame would desync "
                    "from the registry entry")
            if not key_id:
                # api-edge: store naming contract at the serve edge
                raise ValueError("key_id must be a non-empty string")
            payload = (protocol.to_bytes() if protocol is not None
                       else bundle.to_bytes())
            is_proto = (protocol is not None
                        or getattr(bundle, "WIRE_PROTO", 0) != 0)
            staged.append((key_id, payload, is_proto, int(generation)))
        if not staged:
            return 0
        with self._lock:
            entries = self._read_manifest()
            replaced, published = [], 0
            for key_id, payload, is_proto, generation in staged:
                prev = entries.get(key_id)
                if prev is not None and prev["generation"] > generation:
                    continue  # the monotonic guard, per key (see put)
                fname = _frame_name(key_id, generation)
                self._publish(fname, payload, "store.write", key_id)
                if prev is not None and prev["file"] != fname:
                    replaced.append(prev["file"])
                entries[key_id] = {
                    "file": fname,
                    "generation": generation,
                    "proto": is_proto,
                    "parties": 2,
                }
                published += 1
            if published:
                self._write_manifest(entries)  # ONE flip for the batch
                self._c_writes.inc(published)
                for fname in replaced:
                    self._unlink_quiet(fname)
            return published

    def delete_many(self, key_ids) -> int:
        """Drop many keys' durable frames with ONE manifest flip (the
        key factory's batched reclaim of claimed pool frames — a
        per-claim ``delete`` would put a manifest fsync on every
        registration).  Same ordering rule as ``delete``: manifest
        first, then the unlinks, so the published state never
        references a missing file.  Unknown ids are ignored.  Returns
        the number of keys removed."""
        with self._lock:
            entries = self._read_manifest()
            dropped = [entries.pop(key_id) for key_id in dict.fromkeys(
                key_ids) if key_id in entries]
            if not dropped:
                return 0
            self._write_manifest(entries)
            for ent in dropped:
                self._unlink_quiet(ent["file"])
            self._c_deletes.inc(len(dropped))
            return len(dropped)

    def delete(self, key_id: str) -> bool:
        """Drop ``key_id``'s durable frame (manifest first — a crash
        between manifest and unlink leaves an orphan frame, swept
        later — so the published state never references a missing
        file).  Returns whether the key was stored."""
        with self._lock:
            entries = self._read_manifest()
            ent = entries.pop(key_id, None)
            if ent is None:
                return False
            self._write_manifest(entries)
            self._unlink_quiet(ent["file"])
            self._c_deletes.inc()
            return True

    def _unlink_quiet(self, fname: str) -> None:
        try:
            os.unlink(os.path.join(self.root, fname))
        except OSError:
            pass  # already gone (crash window): the manifest is truth

    # -- the restore surface ------------------------------------------------

    def key_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._read_manifest())

    def generation_of(self, key_id: str) -> int:
        with self._lock:
            entries = self._read_manifest()
            if key_id not in entries:
                # api-edge: unknown-name lookup contract at the serve edge
                raise ValueError(f"no durable frame stored under {key_id!r}")
            return entries[key_id]["generation"]

    def load(self, key_id: str):
        """Read back ``key_id`` -> ``(bundle, protocol, generation)``
        with the full wire-format validation.  A frame that fails it —
        truncated, byte-flipped, missing, or inconsistent with its
        manifest entry — is QUARANTINED (renamed aside, manifest entry
        dropped, counter bumped) and surfaces as the typed
        ``KeyQuarantinedError``; the store's other keys are untouched."""
        with self._lock:
            entries = self._read_manifest()
            ent = entries.get(key_id)
            if ent is None:
                # api-edge: unknown-name lookup contract at the serve edge
                raise ValueError(f"no durable frame stored under {key_id!r}")
            return self._load_locked(key_id, ent, entries)

    def load_all(self) -> tuple[dict, dict]:
        """Bulk read for warm restart: ONE manifest read/validation,
        then every frame — ``(loaded: {key_id: (bundle, protocol,
        generation)}, quarantined: {key_id: message})``.  Per-key
        ``load`` calls would re-read and re-validate the whole manifest
        each time (the per-operation re-read is the crash-consistency
        rule for MUTATIONS), making a restore over n keys O(n^2)
        manifest parses on exactly the startup path this store exists
        to make cheap."""
        loaded: dict = {}
        quarantined: dict = {}
        with self._lock:
            entries = self._read_manifest()
            for key_id in sorted(entries):
                try:
                    loaded[key_id] = self._load_locked(
                        key_id, entries[key_id], entries)
                except KeyQuarantinedError as e:
                    quarantined[key_id] = str(e)
        return loaded, quarantined

    def _load_locked(self, key_id: str, ent: dict, entries: dict):
        try:
            with open(os.path.join(self.root, ent["file"]),
                      "rb") as fh:
                data = fh.read()
        except FileNotFoundError as e:
            # The file the manifest references is GONE — that is
            # store damage, quarantine-worthy.  Any other OSError
            # (EMFILE, EACCES, transient fd pressure) propagates
            # UNTOUCHED: quarantining on a condition that clears on
            # retry would permanently destroy a valid durable key —
            # exactly the data loss the store exists to prevent.
            self._quarantine_locked(key_id, ent, entries)
            raise KeyQuarantinedError(
                f"durable frame for {key_id!r} has vanished "
                f"({e}); manifest entry dropped") from e
        try:
            if ent["proto"]:
                from dcf_tpu.protocols import (
                    ProtocolBundle,
                    decode_proto_frame,
                )

                obj = decode_proto_frame(data)
                if isinstance(obj, ProtocolBundle):
                    pb, kb = obj, obj.keys
                else:  # DpfBundle: self-contained, no wrapper record
                    pb, kb = None, obj
            else:
                pb = None
                kb = KeyBundle.from_bytes(data)
            if kb.s0s.shape[1] != ent["parties"]:
                raise KeyFormatError(
                    f"frame stores {kb.s0s.shape[1]} parties, the "
                    f"manifest records {ent['parties']}")
        except KeyFormatError as e:
            self._quarantine_locked(key_id, ent, entries)
            raise KeyQuarantinedError(
                f"durable frame for {key_id!r} failed validation "
                f"and was quarantined ({e})") from e
        return kb, pb, ent["generation"]

    def replicate_to(self, other: "KeyStore", key_id: str, *,
                     retries: int = 3, backoff_s: float = 0.05,
                     sleep=None) -> int:
        """Replicate ``key_id``'s durable frame into ``other``
        PRESERVING its generation (ISSUE 13): the pod provisioning
        primitive — a key placed by the shard ring is written to its
        owner's store and replicated to its replica's, so the host
        CRITICAL traffic fails over to has already restored the key,
        same bytes, same generation, at its next warm start.

        Validation first (``load`` — a frame this store would
        quarantine must not propagate its damage), then ``other``'s
        own atomic-publish + monotonic-generation discipline applies:
        a replica already holding a NEWER generation keeps it.
        Returns the generation replicated.

        Bounded retry (ISSUE 15 satellite): the destination publish is
        retried up to ``retries`` times on a TRANSIENT ``OSError``
        (replica stores live on network mounts in a real pod — a
        one-packet blip must not abort a whole ring migration), with
        ``backoff_s`` doubling between attempts; each retry bumps
        ``serve_store_replicate_retries_total``, and exhaustion raises
        typed ``BackendUnavailableError`` with the last ``OSError``
        cause-chained.  Typed validation failures
        (``KeyQuarantinedError``/``KeyFormatError``) are NEVER retried
        — re-reading damage does not repair it.  ``sleep``: injectable
        for deterministic tests (defaults to ``time.sleep``; pass a
        no-op to retry without waiting)."""
        if retries < 0:
            # api-edge: retry contract (0 = single attempt)
            raise ValueError(f"retries must be >= 0, got {retries}")
        repl_frame = self.load(key_id)  # (bundle, protocol, generation)
        bundle, protocol, generation = repl_frame
        if sleep is None:
            import time

            sleep = time.sleep
        c_retries = self._metrics.counter(
            "serve_store_replicate_retries_total")
        delay = float(backoff_s)
        last: OSError | None = None
        for attempt in range(retries + 1):
            if attempt:
                c_retries.inc()
                sleep(delay)
                delay *= 2
            try:
                other.put(key_id, bundle, protocol=protocol,
                          generation=generation)
                return generation
            except KeyFormatError:
                raise  # destination-side validation: not transient
            except OSError as e:
                last = e
        raise BackendUnavailableError(
            f"replicating {key_id!r} to {other.root!r} failed after "
            f"{retries + 1} attempts (last: {type(last).__name__}: "
            f"{last})") from last

    def quarantine(self, key_id: str) -> None:
        """Set ``key_id``'s stored frame aside explicitly — for callers
        that reject a frame on grounds the codec cannot see (e.g. the
        registry's party check at restore).  A no-op for unknown keys
        or an unreadable manifest (the next real read raises typed)."""
        with self._lock:
            try:
                entries = self._read_manifest()
            except KeyFormatError:
                return
            ent = entries.get(key_id)
            if ent is not None:
                self._quarantine_locked(key_id, ent, entries)

    def digest(self) -> dict:
        """The durable ``{key_id: generation}`` map (ISSUE 14: the
        durable twin of ``KeyRegistry.digest`` — the partition soaks
        assert zero generation regressions against it, and an operator
        can diff a replica store against its owner's without moving a
        byte of key material)."""
        with self._lock:
            return {key_id: ent["generation"]
                    for key_id, ent in self._read_manifest().items()}

    def max_generation(self) -> int:
        """The highest generation any stored frame carries (0 for an
        empty or unreadable store).  A store-backed registry floors its
        generation counter on this at construction, BEFORE any restore:
        a fresh process registering durably into an existing store must
        never mint a generation at or below one the manifest already
        records — ``put``'s monotonic guard would silently drop the
        write-through, un-acking an acked durable registration."""
        with self._lock:
            try:
                entries = self._read_manifest()
            except KeyFormatError:
                return 0  # surfaced typed on the first real read
            return max((ent["generation"] for ent in entries.values()),
                       default=0)

    def _quarantine_locked(self, key_id: str, ent: dict,
                           entries: dict) -> None:
        """Set a damaged frame aside: rename to the first free
        ``.quarantined-<n>`` suffix (preserved for forensics — the
        damage pattern IS the evidence), drop the manifest entry, bump
        the counter.  Never raises: quarantine must not fail the
        failure path."""
        path = os.path.join(self.root, ent["file"])
        n = 0
        while os.path.exists(f"{path}.quarantined-{n}"):
            n += 1
        try:
            os.replace(path, f"{path}.quarantined-{n}")
        except OSError:
            pass  # the frame file itself is gone: nothing to set aside
        entries.pop(key_id, None)
        try:
            self._write_manifest(entries)
        except Exception:  # fallback-ok: quarantine must not fail the
            # failure path — if the manifest publish itself dies here
            # (disk full, or the armed store.manifest seam), the stale
            # entry keeps pointing at the renamed-away file, which the
            # next load re-quarantines via FileNotFoundError; the typed
            # KeyQuarantinedError still reaches the caller either way,
            # and an untyped escape would abort restore for EVERY key.
            pass
        self._c_quarantined.inc()

    def quarantined_files(self) -> list[str]:
        """The set-aside frames currently on disk (basenames, sorted)."""
        with self._lock:
            return sorted(f for f in os.listdir(self.root)
                          if ".quarantined-" in f)

    def sweep_orphans(self) -> int:
        """Remove frame/temp files the manifest does not reference —
        the debris of crash windows between a frame publish and its
        manifest flip (or between a manifest flip and an unlink).
        Quarantined files are kept.  Returns the count removed."""
        with self._lock:
            entries = self._read_manifest()
            live = {ent["file"] for ent in entries.values()}
            live.add(MANIFEST_NAME)
            removed = 0
            for f in os.listdir(self.root):
                if f in live or ".quarantined-" in f:
                    continue
                if f.endswith(_FRAME_SUFFIX) or f.endswith(".tmp"):
                    self._unlink_quiet(f)
                    removed += 1
            return removed
