"""Load generators for the serving layer (``serve_bench`` /
``edge_bench``): closed-loop for throughput, open-loop for latency.

Closed-loop means each client thread keeps exactly one request in
flight: submit -> wait -> submit.  Offered load therefore tracks service
capacity instead of running away from it, which makes the headline
number a genuine sustainable throughput (an open-loop generator against
a saturated service measures its own queue, not the server).

``open_loop`` (ISSUE 12) is the complement the EDGE latency quantiles
need: arrivals are a seeded Poisson process at a FIXED offered rate,
independent of completions.  A closed-loop client that gets stuck
behind a queue simply stops offering load — the classic *coordinated
omission*: the latencies it records are exactly the ones the queueing
delay did not inflate.  The open-loop generator keeps submitting on
schedule and measures each request's latency from its SCHEDULED
arrival time, so queueing delay (and shed/expired outcomes) land in
the numbers instead of disappearing from them.  Refusals record their
typed ``retry_after_s`` hints (``hinted`` per class), and the result's
``sent/ok/shed/expired/failed`` counts reconcile against the service
metrics exactly like ``by_class`` does in the chaos harness.

Clients pick key ids from a seeded RNG over the registered set —
uniformly by default, or Zipf-weighted with ``skew`` > 0 (``key_ids``
order is rank order: p(rank r) ∝ 1/r^skew, the standard model of
skewed production query streams and the shape the serve-resident
frontier cache amortizes) — and draw ragged request sizes uniformly
from ``[min_points, max_points]``, the bursty many-keys shape the
batcher exists for.
Timing uses the SAME injectable clock as the service, so the module
stays clean under the dcflint determinism pass; it is the one
measurement harness allowed to loop on the clock, and the loop bound is
wall duration by design.

``open_loop_ramp`` (ISSUE 16) generalizes the open-loop mode to a
piecewise offered-rate SCHEDULE: ordered ``(duration_s, rate_rps)``
segments driven by the same single seeded arrival process, so a surge
is a first-class load shape — ramp up, hold, fall idle — instead of
three stitched runs whose seams hide the transient.  A zero-rate
segment offers nothing but still holds the schedule (the cool-down
the autoscaler's scale-in hysteresis watches).  Latency stays
anchored to each request's scheduled arrival across segment
boundaries — the coordinated-omission discipline does not bend at
the seams, which is exactly where a saturating ramp would otherwise
hide its queueing delay.  ``open_loop`` is the one-segment special
case and delegates.

``session_churn`` (ISSUE 11) is the fresh-key-per-session variant:
each client registers a fresh key from a key-factory pool, evaluates
one request for both parties, and unregisters — the provisioning-bound
arrival pattern ``keyfactory_bench`` measures, as opposed to
``closed_loop``'s eval-bound re-use of a static key set.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from dcf_tpu.serve.admission import parse_priority
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["LoadgenResult", "closed_loop", "ChurnResult",
           "session_churn", "OpenLoopResult", "open_loop",
           "open_loop_ramp", "reconcile_against_rollup"]


@dataclass
class LoadgenResult:
    """One closed-loop run: totals, latencies, and what was shed.

    ``by_class`` (ISSUE 6): per-priority ``{ok, shed, failed}`` counts —
    the client-side view the chaos harness reconciles against the
    service's ``serve_shed_by_class_total`` metrics (they must agree:
    shedding is observable on both sides of the admission door)."""

    duration_s: float
    requests_ok: int = 0
    points_ok: int = 0
    requests_failed: int = 0
    requests_shed: int = 0
    latencies_s: list = field(default_factory=list)
    by_class: dict = field(default_factory=dict)

    def _count(self, priority: str, outcome: str) -> None:
        cls = self.by_class.setdefault(
            priority, {"ok": 0, "shed": 0, "failed": 0})
        cls[outcome] += 1

    @property
    def throughput(self) -> float:
        """Reconstructed DCF evals/s: points completed per second."""
        return self.points_ok / self.duration_s if self.duration_s else 0.0

    def latency_quantiles(self) -> dict:
        return _quantiles(self.latencies_s, "")


def _n_bytes_of(target) -> int:
    """The point width of any submit target: a ``DcfService``, an
    ``EdgeClient``/``EdgeClientPool``, or a pod ``DcfRouter`` — every
    target carries ``n_bytes`` (the wire-side ones cannot reach
    through the socket; the router carries the pod's)."""
    nb = getattr(target, "n_bytes", None)
    return int(nb) if nb is not None else int(target._dcf.n_bytes)


def reconcile_against_rollup(res, rollup_before: dict,
                             rollup_after: dict) -> dict:
    """Reconcile one loadgen result against a POD metrics rollup
    (ISSUE 13 small fix): the PR 6/12 reconciliation compared client
    counts to ONE service's metrics snapshot, which silently assumed
    one process — behind a router, each class's sheds (and an
    open-loop run's accepted/expired counts) land on WHICHEVER shard
    owned each key, so the server side of the ledger is the SUM over
    hosts (``serve.metrics.rollup_snapshots`` of the shards'
    snapshots), never a single service's.

    ``rollup_before``/``rollup_after``: pod rollups bracketing the
    run (the delta scopes the comparison to this run's traffic; the
    caller must quiesce other load across the bracket).  Returns a
    detail dict with per-class ``{"client": n, "pod": n}`` pairs and
    the overall verdict under ``"reconciled"``.

    What is compared: per-class shed counts (submit-time sheds AND
    evictions both land in ``serve_shed_by_class_total`` — admission
    counts evictions as sheds delivered late) for both result types;
    open-loop results additionally reconcile ``sent`` against
    ``serve_requests_total`` and ``expired`` against
    ``serve_deadline_expired_total``.  Edge-tier refusals that never
    reach a shard queue (tenant token buckets, the router's suspect
    refusals — which clients see as ``CircuitOpenError`` failures,
    not sheds) are deliberately OUTSIDE this ledger: they are counted
    by the tier that refused (``edge_tenant_refusals_total``,
    ``router_suspect_refusals_total``)."""

    def delta(name: str) -> int:
        return (rollup_after.get(name, 0) - rollup_before.get(name, 0))

    out: dict = {}
    ok = True
    by = getattr(res, "by_class", {}) or {}
    for pr in ("critical", "normal", "batch"):
        client = by.get(pr, {}).get("shed", 0)
        pod = delta(f"serve_shed_by_class_total{{priority={pr}}}")
        out[f"shed_{pr}"] = {"client": client, "pod": pod}
        ok = ok and client == pod
    if isinstance(res, OpenLoopResult):
        out["sent"] = {"client": res.sent,
                       "pod": delta("serve_requests_total")}
        out["expired"] = {"client": res.expired,
                          "pod": delta("serve_deadline_expired_total")}
        ok = (ok and out["sent"]["client"] == out["sent"]["pod"]
              and out["expired"]["client"] == out["expired"]["pod"])
    out["reconciled"] = ok
    return out


def _client(service, key_ids, stop: threading.Event, res: LoadgenResult,
            lock: threading.Lock, rng: np.random.Generator,
            min_points: int, max_points: int, b: int, clock,
            priorities, weights, key_probs) -> None:
    from dcf_tpu.errors import QueueFullError

    nb = _n_bytes_of(service)
    while not stop.is_set():
        m = int(rng.integers(min_points, max_points + 1))
        if key_probs is None:
            key_id = key_ids[int(rng.integers(0, len(key_ids)))]
        else:
            key_id = key_ids[int(rng.choice(len(key_ids), p=key_probs))]
        pr = priorities[int(rng.choice(len(priorities), p=weights))]
        xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
        t0 = clock()
        try:
            fut = service.submit(key_id, xs, b=b, priority=pr)
            fut.result()
        except QueueFullError:
            with lock:
                res.requests_shed += 1
                res._count(pr, "shed")
            continue
        except Exception:  # fallback-ok: a client must survive ANY
            # delivered failure — typed DcfErrors AND the raw backend
            # exception a retries-exhausted batch passes through (the
            # chaos harness injects exactly those); a dead client thread
            # silently halves the offered load.
            with lock:
                res.requests_failed += 1
                res._count(pr, "failed")
            continue
        dt = clock() - t0
        with lock:
            res.requests_ok += 1
            res.points_ok += m
            res.latencies_s.append(dt)
            res._count(pr, "ok")


@dataclass
class ChurnResult:
    """One session-churn run (ISSUE 11): per-session outcomes plus the
    two latency populations the key factory exists to separate —
    registration (pool pop vs synchronous keygen) and evaluation."""

    duration_s: float
    sessions_ok: int = 0
    sessions_failed: int = 0
    points_ok: int = 0
    register_latencies_s: list = field(default_factory=list)
    session_latencies_s: list = field(default_factory=list)

    @property
    def sessions_per_sec(self) -> float:
        return (self.sessions_ok / self.duration_s if self.duration_s
                else 0.0)

    def register_quantiles(self) -> dict:
        return _quantiles(self.register_latencies_s, "register_")

    def session_quantiles(self) -> dict:
        return _quantiles(self.session_latencies_s, "session_")


def _quantiles(values, prefix: str) -> dict:
    """The ONE p50/p90/p99 extraction both result types report
    (``prefix`` e.g. ``"register_"``; empty = the plain ``p50_s``
    keys ``LoadgenResult`` has always emitted)."""
    if not values:
        return {}
    arr = np.sort(np.asarray(values))

    def q(p):
        return float(arr[min(int(p * len(arr)), len(arr) - 1)])

    return {f"{prefix}p50_s": round(q(0.50), 6),
            f"{prefix}p90_s": round(q(0.90), 6),
            f"{prefix}p99_s": round(q(0.99), 6)}


def _session_client(service, pool: str, stop: threading.Event,
                    res: ChurnResult, lock: threading.Lock,
                    rng: np.random.Generator, min_points: int,
                    max_points: int, clock, tid: int,
                    durable: bool) -> None:
    nb = service._dcf.n_bytes
    n = 0
    while not stop.is_set():
        key_id = f"~sess/{tid}/{n}"
        n += 1
        m = int(rng.integers(min_points, max_points + 1))
        xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
        t0 = clock()
        try:
            service.register_key(key_id, pool=pool, durable=durable)
            t_reg = clock()
            f0 = service.submit(key_id, xs, b=0)
            f1 = service.submit(key_id, xs, b=1)
            f0.result()
            f1.result()
        except Exception:  # fallback-ok: a churn client must survive
            # ANY delivered failure (sheds, injected refill/eval faults,
            # retries-exhausted errors) — a dead client thread silently
            # halves the offered session arrival
            with lock:
                res.sessions_failed += 1
            try:
                service.unregister_key(key_id)
            except Exception:  # fallback-ok: best-effort cleanup of a
                # session that may never have registered
                pass
            continue
        service.unregister_key(key_id)
        dt = clock() - t0
        with lock:
            res.sessions_ok += 1
            res.points_ok += 2 * m
            res.register_latencies_s.append(max(t_reg - t0, 0.0))
            res.session_latencies_s.append(dt)


def session_churn(service, *, pool: str, duration_s: float,
                  concurrency: int, min_points: int, max_points: int,
                  seed: int = 2026, clock=monotonic,
                  durable: bool = False) -> ChurnResult:
    """Fresh-key-per-session churn (ISSUE 11): each closed-loop client
    repeatedly REGISTERS a fresh session key from the key-factory
    ``pool`` (``register_key(key_id, pool=...)``), evaluates one
    ragged request for BOTH parties, and unregisters — the arrival
    pattern that provisions keys instead of re-using a static set, so
    ``keyfactory_bench``/``serve_bench`` can drive the pool the way
    session traffic does.  The service must be started.  Same seeding
    and clock discipline as ``closed_loop``."""
    if min_points < 1 or min_points > max_points:
        # api-edge: loadgen config contract at the harness edge
        raise ValueError(
            f"bad request-size range [{min_points}, {max_points}]")
    res = ChurnResult(duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_session_client,
            args=(service, pool, stop, res, lock,
                  np.random.default_rng(seed + 13 * i), min_points,
                  max_points, clock, i, durable),
            name=f"churn-{i}", daemon=True)
        for i in range(concurrency)
    ]
    t0 = clock()
    for t in threads:
        t.start()
    # The generator loops on the clock by design: duration IS the bound.
    while clock() - t0 < duration_s:
        stop.wait(0.05)
    stop.set()
    for t in threads:
        t.join()
    res.duration_s = clock() - t0
    return res


@dataclass
class OpenLoopResult:
    """One open-loop (Poisson-arrival) run (ISSUE 12).

    ``sent`` counts submits the service ACCEPTED (they reached the
    queue); ``shed`` counts typed refusals at submit (``shed_hinted``
    of which carried a ``retry_after_s``); accepted requests complete
    as ``ok`` / ``expired`` (``DeadlineExceededError``) / ``failed``
    (``failed`` also absorbs any non-shed submit-time error, so every
    arrival lands in exactly one bucket:
    ``attempts == shed + ok + expired + failed`` after the drain).
    The counts reconcile against the service's
    ``serve_requests_total`` / ``serve_shed_total`` /
    ``serve_deadline_expired_total`` — the same both-sides-of-the-door
    discipline as ``by_class``.

    ``latencies_s`` measure from each request's SCHEDULED arrival to
    completion — the anti-coordinated-omission definition: a request
    delayed by the queue (or by the generator catching up after a
    stall) is charged that delay, so ``p99`` reflects what an
    independent caller would have seen at this offered rate."""

    duration_s: float
    offered_rps: float
    sent: int = 0
    shed: int = 0
    shed_hinted: int = 0
    ok: int = 0
    expired: int = 0
    failed: int = 0
    points_ok: int = 0
    latencies_s: list = field(default_factory=list)
    by_class: dict = field(default_factory=dict)
    #: The offered-rate schedule (ISSUE 16): ``[(duration_s,
    #: rate_rps), ...]`` — one entry for a plain ``open_loop`` run;
    #: ``offered_rps`` is its duration-weighted mean.
    offered_segments: list = field(default_factory=list)

    def _count(self, priority: str, outcome: str) -> None:
        cls = self.by_class.setdefault(
            priority, {"ok": 0, "shed": 0, "expired": 0, "failed": 0})
        cls[outcome] += 1

    @property
    def attempts(self) -> int:
        """Every scheduled arrival (exact after the drain: each lands
        in exactly one of shed/ok/expired/failed)."""
        return self.shed + self.ok + self.expired + self.failed

    @property
    def throughput(self) -> float:
        """Completed evals/s: points of OK requests per second."""
        return self.points_ok / self.duration_s if self.duration_s \
            else 0.0

    def latency_quantiles(self) -> dict:
        return _quantiles(self.latencies_s, "")


def _open_collector(out_q, res: OpenLoopResult, lock: threading.Lock,
                    clock) -> None:
    from dcf_tpu.errors import DeadlineExceededError, QueueFullError

    while True:
        item = out_q.get()
        if item is None:
            return
        fut, t_sched, m, pr = item
        try:
            fut.result()
        except QueueFullError as e:
            # Refusals delivered through the future are sheds.  Two
            # flavors: the WIRE path's submit-time refusal (the server
            # shed BEFORE acceptance, after the local submit already
            # succeeded) retracts the ``sent`` — "sent" must mean "the
            # SERVICE accepted it" on both paths or the
            # serve_requests_total reconciliation breaks — while an
            # EVICTION (``e.evicted``) was accepted and counted before
            # losing its room, so its ``sent`` stands.
            with lock:
                if not getattr(e, "evicted", False):
                    res.sent -= 1
                res.shed += 1
                if getattr(e, "retry_after_s", None) is not None:
                    res.shed_hinted += 1
                res._count(pr, "shed")
            continue
        except DeadlineExceededError:
            with lock:
                res.expired += 1
                res._count(pr, "expired")
            continue
        except Exception:  # fallback-ok: a collector must survive ANY
            # delivered failure (typed DcfErrors and the raw backend
            # exception a retries-exhausted batch passes through) —
            # a dead collector would wedge the drain.
            with lock:
                res.failed += 1
                res._count(pr, "failed")
            continue
        dt = clock() - t_sched
        with lock:
            res.ok += 1
            res.points_ok += m
            res.latencies_s.append(max(dt, 0.0))
            res._count(pr, "ok")


def open_loop(service, key_ids, *, rate_rps: float, duration_s: float,
              min_points: int, max_points: int, seed: int = 2026,
              party: int = 0, clock=monotonic,
              priority_mix: dict | None = None, skew: float = 0.0,
              deadline_ms: float | None = None,
              collectors: int = 4) -> OpenLoopResult:
    """Offer ``rate_rps`` requests/s of Poisson arrivals to ``service``
    (a ``DcfService`` or an ``EdgeClient`` — anything with ``submit``)
    for ``duration_s`` seconds, independent of completions, and return
    the ``OpenLoopResult``.  The service must be started.

    Arrivals are a seeded renewal process: inter-arrival gaps are
    exponential draws from ONE rng, so the whole arrival schedule (and
    every per-request key/size/priority draw) replays exactly per
    seed.  One scheduler thread submits on schedule; ``collectors``
    threads drain the futures so a slow completion never back-pressures
    the arrival process (that back-pressure is exactly the closed-loop
    artifact this mode exists to remove).  The run always DRAINS: every
    accepted future is collected before returning, however late.

    ``deadline_ms`` is attached to every request — under overload the
    service converts queue delay into typed ``DeadlineExceededError``
    expiries, which the result counts separately from failures."""
    import math

    if not rate_rps > 0 or not math.isfinite(rate_rps):
        # api-edge: loadgen config contract at the harness edge
        raise ValueError(
            f"rate_rps must be finite and > 0, got {rate_rps}")
    return open_loop_ramp(
        service, key_ids, segments=[(duration_s, rate_rps)],
        min_points=min_points, max_points=max_points, seed=seed,
        party=party, clock=clock, priority_mix=priority_mix,
        skew=skew, deadline_ms=deadline_ms, collectors=collectors)


def open_loop_ramp(service, key_ids, *, segments, min_points: int,
                   max_points: int, seed: int = 2026, party: int = 0,
                   clock=monotonic, priority_mix: dict | None = None,
                   skew: float = 0.0, deadline_ms: float | None = None,
                   collectors: int = 4) -> OpenLoopResult:
    """Offer a piecewise schedule of Poisson arrivals (ISSUE 16):
    ``segments`` is an ordered list of ``(duration_s, rate_rps)``
    pairs, played back-to-back by ONE seeded arrival process — the
    surge shape (``ramp up -> hold -> fall idle``) as a single run, so
    the transient at each boundary lands in the same
    coordinated-omission-free latency population instead of being
    split across stitched runs.  ``rate_rps`` may be 0: a quiet
    segment offers nothing but still holds the schedule (the
    autoscaler's idle window).  A draw that lands past its segment's
    end is clamped to the boundary and the next segment's rate takes
    over there (seeded-deterministic, like everything else here).
    Everything not named ``segments`` behaves exactly as in
    ``open_loop`` — which is the one-segment special case of this."""
    import math
    import queue as _queue

    segs = [(float(d), float(r)) for d, r in segments]
    if not segs:
        # api-edge: loadgen config contract at the harness edge
        raise ValueError("segments must be non-empty")
    for d, r in segs:
        if not (d > 0 and math.isfinite(d)) \
                or not (r >= 0 and math.isfinite(r)):
            # api-edge: loadgen config contract at the harness edge —
            # a zero-duration or negative-rate segment is a schedule
            # typo, not a load shape
            raise ValueError(
                f"each segment needs duration > 0 and rate >= 0, "
                f"got ({d}, {r})")
    if min_points < 1 or min_points > max_points:
        # api-edge: loadgen config contract at the harness edge
        raise ValueError(
            f"bad request-size range [{min_points}, {max_points}]")
    if not math.isfinite(skew) or skew < 0:
        # api-edge: same contract as closed_loop
        raise ValueError(f"skew must be finite and >= 0, got {skew}")
    from dcf_tpu.errors import QueueFullError

    key_ids = list(key_ids)
    key_probs = None
    if skew > 0:
        ranks = np.arange(1, len(key_ids) + 1, dtype=np.float64)
        w = ranks ** -float(skew)
        key_probs = w / w.sum()
    if priority_mix:
        priorities = sorted(priority_mix)
        for p in priorities:
            parse_priority(p)  # typos die here, not per-arrival
        total = float(sum(priority_mix.values()))
        if total <= 0 or min(priority_mix.values()) < 0:
            # api-edge: same contract as closed_loop
            raise ValueError(
                f"priority_mix weights must be >= 0 and sum > 0, "
                f"got {priority_mix}")
        weights = [priority_mix[p] / total for p in priorities]
    else:
        priorities, weights = ["normal"], [1.0]

    nb = _n_bytes_of(service)
    rng = np.random.default_rng(seed)
    total_s = sum(d for d, _r in segs)
    mean_rps = sum(d * r for d, r in segs) / total_s
    res = OpenLoopResult(duration_s=0.0, offered_rps=mean_rps,
                         offered_segments=segs)
    lock = threading.Lock()
    out_q: _queue.Queue = _queue.Queue()
    pool = [threading.Thread(target=_open_collector,
                             args=(out_q, res, lock, clock),
                             name=f"openloop-collect-{i}", daemon=True)
            for i in range(max(collectors, 1))]
    for t in pool:
        t.start()
    # Purely a wait primitive (never set): the run is NOT cancellable
    # — the arrival schedule is the load definition and only the
    # schedule check ends the loop.
    sleeper = threading.Event()
    t0 = clock()
    seg_end = t0
    # The scheduler loops on the clock by design: the arrival SCHEDULE
    # is the load definition, and latency is measured from it.
    for seg_s, rate_rps in segs:
        t_next = seg_end  # a draw past the boundary was clamped here
        seg_end = seg_end + seg_s
        if rate_rps == 0:
            # Quiet segment: offer nothing, hold the schedule.
            wait = seg_end - clock()
            if wait > 0:
                sleeper.wait(wait)
            continue
        while True:
            t_next += float(rng.exponential(1.0 / rate_rps))
            if t_next >= seg_end:
                break
            wait = t_next - clock()
            if wait > 0:
                sleeper.wait(wait)
            m = int(rng.integers(min_points, max_points + 1))
            if key_probs is None:
                key_id = key_ids[int(rng.integers(0, len(key_ids)))]
            else:
                key_id = key_ids[int(
                    rng.choice(len(key_ids), p=key_probs))]
            pr = priorities[int(rng.choice(len(priorities), p=weights))]
            xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
            try:
                fut = service.submit(
                    key_id, xs, b=party,
                    deadline_ms=deadline_ms, priority=pr)
            except QueueFullError as e:
                with lock:
                    res.shed += 1
                    if getattr(e, "retry_after_s", None) is not None:
                        res.shed_hinted += 1
                    res._count(pr, "shed")
                continue
            except Exception:  # fallback-ok: the scheduler must
                # survive ANY submit-time failure (e.g. a hot-swapped
                # key) — a dead scheduler silently truncates the
                # offered load.
                with lock:
                    res.failed += 1
                    res._count(pr, "failed")
                continue
            with lock:
                res.sent += 1
            out_q.put((fut, t_next, m, pr))
    # Drain: every accepted future completes (the service's contract),
    # so the collectors empty the queue and exit on their sentinels.
    for _ in pool:
        out_q.put(None)
    for t in pool:
        t.join()
    res.duration_s = clock() - t0
    return res


def closed_loop(service, key_ids, *, duration_s: float, concurrency: int,
                min_points: int, max_points: int, seed: int = 2026,
                party: int = 0, clock=monotonic,
                priority_mix: dict | None = None,
                skew: float = 0.0, clients=None) -> LoadgenResult:
    """Drive ``service`` with ``concurrency`` closed-loop clients for
    ``duration_s`` seconds of wall time; returns the aggregated result.
    The service must be started (worker thread running).

    ``priority_mix``: ``{"critical": w, "normal": w, "batch": w}``
    weights (normalized here) drawn per request from the client's seeded
    RNG; default is the pre-priority behaviour (all NORMAL).

    ``skew``: Zipf exponent for key choice — 0 (default) is uniform;
    s > 0 weights rank r (the r-th entry of ``key_ids``) by 1/r^s,
    normalized.  Must be finite and >= 0 (the CLI benches validate the
    ``--skew`` flag before spending warmup time; this is the API-edge
    backstop).

    ``clients`` (ISSUE 12): one submit target PER THREAD — the wire
    mode.  ``edge_bench`` passes a list of ``concurrency`` connected
    ``EdgeClient``s so each closed-loop client drives its own TCP
    connection (the in-process default shares the one ``service``)."""
    import math

    if not math.isfinite(skew) or skew < 0:
        # api-edge: loadgen config contract at the harness edge — a
        # negative or NaN exponent would die inside rng.choice in every
        # client thread, silently zeroing the offered load
        raise ValueError(f"skew must be finite and >= 0, got {skew}")
    key_probs = None
    if skew > 0:
        ranks = np.arange(1, len(list(key_ids)) + 1, dtype=np.float64)
        w = ranks ** -float(skew)
        key_probs = w / w.sum()
    if priority_mix:
        priorities = sorted(priority_mix)
        for p in priorities:
            # Unknown class names die here at the edge, not as a
            # parse_priority ValueError inside every client thread
            # (which _client's broadened except would count as
            # requests_failed — a 100%-failed run with no loud error).
            parse_priority(p)
        total = float(sum(priority_mix.values()))
        if total <= 0 or min(priority_mix.values()) < 0:
            # api-edge: loadgen config contract at the harness edge — a
            # negative weight would kill every client thread inside
            # rng.choice, silently zeroing the offered load
            raise ValueError(
                f"priority_mix weights must be >= 0 and sum > 0, "
                f"got {priority_mix}")
        weights = [priority_mix[p] / total for p in priorities]
    else:
        priorities, weights = ["normal"], [1.0]
    if clients is not None and len(clients) != concurrency:
        # api-edge: loadgen config contract at the harness edge — a
        # short list would silently drop offered load
        raise ValueError(
            f"clients must hold one target per thread "
            f"({concurrency}), got {len(clients)}")
    res = LoadgenResult(duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_client,
            args=(clients[i] if clients is not None else service,
                  list(key_ids), stop, res, lock,
                  np.random.default_rng(seed + 7 * i), min_points,
                  max_points, party, clock, priorities, weights,
                  key_probs),
            name=f"loadgen-{i}", daemon=True)
        for i in range(concurrency)
    ]
    t0 = clock()
    for t in threads:
        t.start()
    # The generator loops on the clock by design: duration IS the bound.
    while clock() - t0 < duration_s:
        stop.wait(0.05)
    stop.set()
    for t in threads:
        t.join()
    res.duration_s = clock() - t0
    return res
