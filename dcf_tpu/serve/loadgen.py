"""Closed-loop load generator for the serving layer (``serve_bench``).

Closed-loop means each client thread keeps exactly one request in
flight: submit -> wait -> submit.  Offered load therefore tracks service
capacity instead of running away from it, which makes the headline
number a genuine sustainable throughput (an open-loop generator against
a saturated service measures its own queue, not the server).

Clients pick key ids from a seeded RNG over the registered set —
uniformly by default, or Zipf-weighted with ``skew`` > 0 (``key_ids``
order is rank order: p(rank r) ∝ 1/r^skew, the standard model of
skewed production query streams and the shape the serve-resident
frontier cache amortizes) — and draw ragged request sizes uniformly
from ``[min_points, max_points]``, the bursty many-keys shape the
batcher exists for.
Timing uses the SAME injectable clock as the service, so the module
stays clean under the dcflint determinism pass; it is the one
measurement harness allowed to loop on the clock, and the loop bound is
wall duration by design.

``session_churn`` (ISSUE 11) is the fresh-key-per-session variant:
each client registers a fresh key from a key-factory pool, evaluates
one request for both parties, and unregisters — the provisioning-bound
arrival pattern ``keyfactory_bench`` measures, as opposed to
``closed_loop``'s eval-bound re-use of a static key set.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from dcf_tpu.serve.admission import parse_priority
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["LoadgenResult", "closed_loop", "ChurnResult",
           "session_churn"]


@dataclass
class LoadgenResult:
    """One closed-loop run: totals, latencies, and what was shed.

    ``by_class`` (ISSUE 6): per-priority ``{ok, shed, failed}`` counts —
    the client-side view the chaos harness reconciles against the
    service's ``serve_shed_by_class_total`` metrics (they must agree:
    shedding is observable on both sides of the admission door)."""

    duration_s: float
    requests_ok: int = 0
    points_ok: int = 0
    requests_failed: int = 0
    requests_shed: int = 0
    latencies_s: list = field(default_factory=list)
    by_class: dict = field(default_factory=dict)

    def _count(self, priority: str, outcome: str) -> None:
        cls = self.by_class.setdefault(
            priority, {"ok": 0, "shed": 0, "failed": 0})
        cls[outcome] += 1

    @property
    def throughput(self) -> float:
        """Reconstructed DCF evals/s: points completed per second."""
        return self.points_ok / self.duration_s if self.duration_s else 0.0

    def latency_quantiles(self) -> dict:
        return _quantiles(self.latencies_s, "")


def _client(service, key_ids, stop: threading.Event, res: LoadgenResult,
            lock: threading.Lock, rng: np.random.Generator,
            min_points: int, max_points: int, b: int, clock,
            priorities, weights, key_probs) -> None:
    from dcf_tpu.errors import QueueFullError

    nb = service._dcf.n_bytes
    while not stop.is_set():
        m = int(rng.integers(min_points, max_points + 1))
        if key_probs is None:
            key_id = key_ids[int(rng.integers(0, len(key_ids)))]
        else:
            key_id = key_ids[int(rng.choice(len(key_ids), p=key_probs))]
        pr = priorities[int(rng.choice(len(priorities), p=weights))]
        xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
        t0 = clock()
        try:
            fut = service.submit(key_id, xs, b=b, priority=pr)
            fut.result()
        except QueueFullError:
            with lock:
                res.requests_shed += 1
                res._count(pr, "shed")
            continue
        except Exception:  # fallback-ok: a client must survive ANY
            # delivered failure — typed DcfErrors AND the raw backend
            # exception a retries-exhausted batch passes through (the
            # chaos harness injects exactly those); a dead client thread
            # silently halves the offered load.
            with lock:
                res.requests_failed += 1
                res._count(pr, "failed")
            continue
        dt = clock() - t0
        with lock:
            res.requests_ok += 1
            res.points_ok += m
            res.latencies_s.append(dt)
            res._count(pr, "ok")


@dataclass
class ChurnResult:
    """One session-churn run (ISSUE 11): per-session outcomes plus the
    two latency populations the key factory exists to separate —
    registration (pool pop vs synchronous keygen) and evaluation."""

    duration_s: float
    sessions_ok: int = 0
    sessions_failed: int = 0
    points_ok: int = 0
    register_latencies_s: list = field(default_factory=list)
    session_latencies_s: list = field(default_factory=list)

    @property
    def sessions_per_sec(self) -> float:
        return (self.sessions_ok / self.duration_s if self.duration_s
                else 0.0)

    def register_quantiles(self) -> dict:
        return _quantiles(self.register_latencies_s, "register_")

    def session_quantiles(self) -> dict:
        return _quantiles(self.session_latencies_s, "session_")


def _quantiles(values, prefix: str) -> dict:
    """The ONE p50/p90/p99 extraction both result types report
    (``prefix`` e.g. ``"register_"``; empty = the plain ``p50_s``
    keys ``LoadgenResult`` has always emitted)."""
    if not values:
        return {}
    arr = np.sort(np.asarray(values))

    def q(p):
        return float(arr[min(int(p * len(arr)), len(arr) - 1)])

    return {f"{prefix}p50_s": round(q(0.50), 6),
            f"{prefix}p90_s": round(q(0.90), 6),
            f"{prefix}p99_s": round(q(0.99), 6)}


def _session_client(service, pool: str, stop: threading.Event,
                    res: ChurnResult, lock: threading.Lock,
                    rng: np.random.Generator, min_points: int,
                    max_points: int, clock, tid: int,
                    durable: bool) -> None:
    nb = service._dcf.n_bytes
    n = 0
    while not stop.is_set():
        key_id = f"~sess/{tid}/{n}"
        n += 1
        m = int(rng.integers(min_points, max_points + 1))
        xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
        t0 = clock()
        try:
            service.register_key(key_id, pool=pool, durable=durable)
            t_reg = clock()
            f0 = service.submit(key_id, xs, b=0)
            f1 = service.submit(key_id, xs, b=1)
            f0.result()
            f1.result()
        except Exception:  # fallback-ok: a churn client must survive
            # ANY delivered failure (sheds, injected refill/eval faults,
            # retries-exhausted errors) — a dead client thread silently
            # halves the offered session arrival
            with lock:
                res.sessions_failed += 1
            try:
                service.unregister_key(key_id)
            except Exception:  # fallback-ok: best-effort cleanup of a
                # session that may never have registered
                pass
            continue
        service.unregister_key(key_id)
        dt = clock() - t0
        with lock:
            res.sessions_ok += 1
            res.points_ok += 2 * m
            res.register_latencies_s.append(max(t_reg - t0, 0.0))
            res.session_latencies_s.append(dt)


def session_churn(service, *, pool: str, duration_s: float,
                  concurrency: int, min_points: int, max_points: int,
                  seed: int = 2026, clock=monotonic,
                  durable: bool = False) -> ChurnResult:
    """Fresh-key-per-session churn (ISSUE 11): each closed-loop client
    repeatedly REGISTERS a fresh session key from the key-factory
    ``pool`` (``register_key(key_id, pool=...)``), evaluates one
    ragged request for BOTH parties, and unregisters — the arrival
    pattern that provisions keys instead of re-using a static set, so
    ``keyfactory_bench``/``serve_bench`` can drive the pool the way
    session traffic does.  The service must be started.  Same seeding
    and clock discipline as ``closed_loop``."""
    if min_points < 1 or min_points > max_points:
        # api-edge: loadgen config contract at the harness edge
        raise ValueError(
            f"bad request-size range [{min_points}, {max_points}]")
    res = ChurnResult(duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_session_client,
            args=(service, pool, stop, res, lock,
                  np.random.default_rng(seed + 13 * i), min_points,
                  max_points, clock, i, durable),
            name=f"churn-{i}", daemon=True)
        for i in range(concurrency)
    ]
    t0 = clock()
    for t in threads:
        t.start()
    # The generator loops on the clock by design: duration IS the bound.
    while clock() - t0 < duration_s:
        stop.wait(0.05)
    stop.set()
    for t in threads:
        t.join()
    res.duration_s = clock() - t0
    return res


def closed_loop(service, key_ids, *, duration_s: float, concurrency: int,
                min_points: int, max_points: int, seed: int = 2026,
                party: int = 0, clock=monotonic,
                priority_mix: dict | None = None,
                skew: float = 0.0) -> LoadgenResult:
    """Drive ``service`` with ``concurrency`` closed-loop clients for
    ``duration_s`` seconds of wall time; returns the aggregated result.
    The service must be started (worker thread running).

    ``priority_mix``: ``{"critical": w, "normal": w, "batch": w}``
    weights (normalized here) drawn per request from the client's seeded
    RNG; default is the pre-priority behaviour (all NORMAL).

    ``skew``: Zipf exponent for key choice — 0 (default) is uniform;
    s > 0 weights rank r (the r-th entry of ``key_ids``) by 1/r^s,
    normalized.  Must be finite and >= 0 (the CLI benches validate the
    ``--skew`` flag before spending warmup time; this is the API-edge
    backstop)."""
    import math

    if not math.isfinite(skew) or skew < 0:
        # api-edge: loadgen config contract at the harness edge — a
        # negative or NaN exponent would die inside rng.choice in every
        # client thread, silently zeroing the offered load
        raise ValueError(f"skew must be finite and >= 0, got {skew}")
    key_probs = None
    if skew > 0:
        ranks = np.arange(1, len(list(key_ids)) + 1, dtype=np.float64)
        w = ranks ** -float(skew)
        key_probs = w / w.sum()
    if priority_mix:
        priorities = sorted(priority_mix)
        for p in priorities:
            # Unknown class names die here at the edge, not as a
            # parse_priority ValueError inside every client thread
            # (which _client's broadened except would count as
            # requests_failed — a 100%-failed run with no loud error).
            parse_priority(p)
        total = float(sum(priority_mix.values()))
        if total <= 0 or min(priority_mix.values()) < 0:
            # api-edge: loadgen config contract at the harness edge — a
            # negative weight would kill every client thread inside
            # rng.choice, silently zeroing the offered load
            raise ValueError(
                f"priority_mix weights must be >= 0 and sum > 0, "
                f"got {priority_mix}")
        weights = [priority_mix[p] / total for p in priorities]
    else:
        priorities, weights = ["normal"], [1.0]
    res = LoadgenResult(duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_client,
            args=(service, list(key_ids), stop, res, lock,
                  np.random.default_rng(seed + 7 * i), min_points,
                  max_points, party, clock, priorities, weights,
                  key_probs),
            name=f"loadgen-{i}", daemon=True)
        for i in range(concurrency)
    ]
    t0 = clock()
    for t in threads:
        t.start()
    # The generator loops on the clock by design: duration IS the bound.
    while clock() - t0 < duration_s:
        stop.wait(0.05)
    stop.set()
    for t in threads:
        t.join()
    res.duration_s = clock() - t0
    return res
