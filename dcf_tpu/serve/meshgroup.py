"""Mesh co-evaluation group (ISSUE 18): the serving tier's device-
placement plan for one batch spanning every host.

The pod has two orthogonal placements, and this module exists to keep
them separate:

* the **ring** (``serve.shardmap``) places KEYS: rendezvous hashing
  decides which host owns which key, and route-mode dispatch sends a
  request to its key's owner — one host, one key;
* the **mesh** (this module) places DEVICES: a co-evaluated batch is
  split into contiguous point slices, one per mesh worker, every
  worker evaluates the SAME key over its slice, and the router
  concatenates the shares back in plan order — all hosts, one batch.

``MeshGroup`` is the mesh analogue of ``ShardMap`` and follows its
discipline: pure placement — no sockets, no health state (the router
owns suspicion and degradation), no clocks — and immutable, so an
in-flight co-evaluation keeps the plan it started with while the
router re-forms the group.  Formation is EPOCH-FENCED (ISSUE 15
machinery): a group remembers the ring epoch it was formed under, and
the router refuses to scatter over a group whose epoch trails the
current ring — membership moved, the worker set may be stale, the
group must be re-formed (``MeshUnavailableError`` / degrade to
route-mode, never a scatter onto ejected hosts).

Slices are 32-point aligned: the shard batcher packs points into
32-lane words, so a misaligned split would force every worker after
the first into a re-pack of its whole slice — alignment keeps the
zero-copy relay (PR 12/13) intact across the scatter.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["MeshGroup", "MeshSlice"]

# The batcher's lane-word width: co-evaluate slice boundaries land on
# multiples of it so every scattered sub-view stays pack-aligned.
SLICE_ALIGN = 32


@dataclass(frozen=True)
class MeshSlice:
    """One worker's contiguous share of a co-evaluated batch:
    ``count`` points starting at ``offset`` of the caller's order
    (gather concatenates the slices back in this order)."""

    host_id: str
    offset: int
    count: int


class MeshGroup:
    """Immutable co-evaluation group over a set of worker host ids.

    ``host_ids``: the ring members that take scattered slices — stored
    sorted, same set-not-list discipline as ``ShardMap`` (two routers
    forming the group from the same members agree on the plan).
    ``epoch``: the ring epoch at formation — the fence the router
    checks before every scatter."""

    def __init__(self, host_ids, *, epoch: int = 0):
        ids = tuple(host_ids)
        if not ids:
            # api-edge: mesh membership contract — an empty group has
            # nobody to scatter to; the router clears the group instead
            raise ValueError("a mesh group needs at least one worker")
        if len(set(ids)) != len(ids):
            # api-edge: mesh membership contract — a duplicated worker
            # would be handed two slices of the same batch
            raise ValueError(f"duplicate mesh worker host_ids in "
                             f"{list(ids)}")
        self._ids = tuple(sorted(ids))
        self._epoch = int(epoch)

    @property
    def epoch(self) -> int:
        return self._epoch

    def host_ids(self) -> list[str]:
        return list(self._ids)

    def __len__(self) -> int:
        return len(self._ids)

    def __contains__(self, host_id: str) -> bool:
        return host_id in self._ids

    def plan(self, m: int) -> list[MeshSlice]:
        """Split an ``m``-point batch into per-worker slices.

        Contiguous, in worker (sorted host_id) order, every boundary a
        multiple of ``SLICE_ALIGN`` except the batch end; lane words
        are dealt round-robin-evenly (first workers take the remainder
        word), and a worker whose share rounds to zero words takes no
        slice — a 17-point batch over 8 workers is ONE slice, not
        seven empty scatters."""
        if m < 1:
            # api-edge: plan contract — the router validates payloads
            # before planning, so an empty plan is a caller bug
            raise ValueError(f"cannot plan a {m}-point batch")
        words = -(-m // SLICE_ALIGN)
        n = len(self._ids)
        base, rem = divmod(words, n)
        slices: list[MeshSlice] = []
        offset = 0
        for i, host_id in enumerate(self._ids):
            w = base + (1 if i < rem else 0)
            if w == 0:
                continue
            count = min(w * SLICE_ALIGN, m - offset)
            if count <= 0:
                break
            slices.append(MeshSlice(host_id, offset, count))
            offset += count
        return slices

    def __repr__(self) -> str:
        return f"MeshGroup({list(self._ids)}, epoch={self._epoch})"
