"""Serve-resident frontier cache: amortize the narrow-walk floor.

ROOFLINE round 6 put further cold-eval speedups at the cipher wall — the
lam-independent mid-λ residue IS the narrow GGM walk, and the remaining
lever is amortization.  The prefix-family backends already expand the
top-k walk levels into a per-(key image, party) frontier (64 B x 2^k x K
rows for the hybrid, 32 B rows at lam=16), but before this module the
expansion was an *instance* asset: it died with every LRU residency
eviction and was rebuilt from scratch on the next re-stage.  Under
Zipf-skewed production traffic that rebuild is pure waste — the key's
image churns, the key's *function* does not.

``FrontierCache`` promotes the frontier to a *serve* asset:

* an LRU keyed by ``(key_id, generation, party, k)`` living beside the
  ``serve.registry.KeyRegistry`` — the registration generation is part
  of the key so a hot-swapped bundle can never alias the old frontier;
* charged against the registry's existing ``device_bytes_budget``: both
  populations (staged key images and cached frontiers) share ONE budget
  and ONE deterministic LRU stamp sequence (``TickSource``), so "evict
  the coldest thing" is well-defined across them and the budget math
  stays exact (frontier rows have a fixed byte cost per node);
* populated off the eval clock — the registry warms the frontier at
  stage time (``FrontierConsumerMixin.ensure_frontier``) and any miss
  builds on first consult — and invalidated through the same
  generation-bump hook as residencies (``KeyRegistry._evict_entry``):
  hot-swap, unregister and failure eviction drop a key's frontiers;
  a pure LRU *budget* eviction of the residency keeps them (that
  survival is the amortization).

Observability (all through the shared ``serve.metrics.Metrics``):
``serve_frontier_hits_total`` / ``serve_frontier_misses_total``
(consults per eval), ``serve_frontier_evictions_total``, and the
``serve_frontier_cache_bytes`` / ``serve_frontier_cache_entries``
gauges.  Hit rate = hits / (hits + misses) is the number
``serve_bench --skew`` reports.

Thread safety: one lock per cache; builds run OUTSIDE it (a frontier
expansion dispatches real device work — holding the lock would
serialize unrelated keys).  Two threads racing the same miss may both
build; the first insert wins and both results are bit-identical (the
frontier is a pure function of the key image), so the race costs work,
never correctness.  A build racing an INVALIDATION is the dangerous
case — its tables were computed against state just declared dead or
superseded — so ``invalidate_key``/``invalidate_all`` bump an epoch
that ``get`` snapshots before building and re-checks before inserting:
the raced result is handed to its in-flight caller (whose batch fails
or retries through the service's reset path anyway) but never
persisted.  LRU stamps come from the shared ``TickSource`` — a
lock of its own, never held while calling out — so eviction order is a
pure function of the request sequence (the dcflint determinism
contract; tests pin exact orders).
"""

from __future__ import annotations

import threading

from dcf_tpu.serve.metrics import Metrics

__all__ = ["FrontierCache", "TickSource", "tables_nbytes"]


class TickSource:
    """Deterministic shared LRU clock: a strictly increasing counter
    handed out per access event.  Shared between a ``KeyRegistry`` and
    its ``FrontierCache`` so their merged eviction order is total."""

    __slots__ = ("_lock", "_tick")

    def __init__(self):
        self._lock = threading.Lock()
        self._tick = 0

    def next(self) -> int:
        with self._lock:
            self._tick += 1
            return self._tick


class _CacheEntry:
    __slots__ = ("tables", "bytes", "stamp")

    def __init__(self, tables, nbytes: int, stamp: int):
        self.tables = tables
        self.bytes = nbytes
        self.stamp = stamp

    def __repr__(self) -> str:  # never table contents — key material
        return f"_CacheEntry(bytes={self.bytes}, stamp={self.stamp})"


def tables_nbytes(tables) -> int:
    """Device bytes of one frontier holding (a table array or a tuple
    of them — the hybrid's (state rows, trajectory words)).  The ONE
    byte-accounting rule for both merged-budget populations: the cache
    uses it per entry, ``registry.device_image_bytes`` per image-dict
    value — they must never drift apart or the shared budget compares
    apples to oranges."""
    if isinstance(tables, tuple):
        return sum(int(getattr(t, "nbytes", 0) or 0) for t in tables)
    return int(getattr(tables, "nbytes", 0) or 0)


class _BoundProvider:
    """The narrow provider a backend instance consults
    (``backends.frontier.FrontierConsumerMixin.frontier_provider``):
    one cache binding per (key_id, registration generation), created by
    ``FrontierCache.bind`` when the registry stages a residency."""

    __slots__ = ("_cache", "_key_id", "_generation")

    def __init__(self, cache: "FrontierCache", key_id: str,
                 generation: int):
        self._cache = cache
        self._key_id = key_id
        self._generation = generation

    def get(self, party: int, k: int, build):
        return self._cache.get(
            (self._key_id, self._generation, int(party), int(k)), build)

    def __repr__(self) -> str:
        return (f"_BoundProvider(key_id={self._key_id!r}, "
                f"gen={self._generation})")


class FrontierCache:
    """LRU over expanded prefix frontiers (see module docstring).

    ``ticks``: the shared ``TickSource`` (the registry adopts it);
    ``on_growth``: zero-arg hook run after every insert, OUTSIDE the
    cache lock — the registry hangs its merged budget enforcement here.
    """

    def __init__(self, *, metrics: Metrics | None = None,
                 ticks: TickSource | None = None):
        self._lock = threading.Lock()
        self._entries: dict[tuple, _CacheEntry] = {}
        # Invalidation epoch: bumped by invalidate_key/invalidate_all so
        # a build that was in flight when an invalidation swept the
        # cache cannot re-insert state computed against a dead or
        # superseded backend (builds run outside the lock; without the
        # epoch check the raced insert would outlive the shared
        # reset_backend_health path).
        self._epoch = 0
        self.ticks = ticks if ticks is not None else TickSource()
        self._on_growth = None
        m = metrics if metrics is not None else Metrics()
        self._c_hits = m.counter("serve_frontier_hits_total")
        self._c_misses = m.counter("serve_frontier_misses_total")
        self._c_evictions = m.counter("serve_frontier_evictions_total")
        self._g_bytes = m.gauge("serve_frontier_cache_bytes")
        self._g_entries = m.gauge("serve_frontier_cache_entries")

    # -- the provider side (consulted by backends) --------------------------

    def bind(self, key_id: str, generation: int) -> _BoundProvider:
        """A provider scoped to one (key_id, registration generation) —
        set on a residency's backend instance right after
        ``put_bundle`` (which unbinds any previous one)."""
        return _BoundProvider(self, key_id, int(generation))

    def get(self, key: tuple, build):
        """The cached tables under ``key``, building (outside the lock)
        and inserting on a miss.  Every consult re-stamps the entry —
        recency is per eval, not per staging."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                ent.stamp = self.ticks.next()
                self._c_hits.inc()
                return ent.tables
            epoch = self._epoch
        self._c_misses.inc()
        tables = build()
        grew = False
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:  # a concurrent miss inserted first
                ent.stamp = self.ticks.next()
                tables = ent.tables
            elif self._epoch != epoch:
                # An invalidation swept the cache mid-build: these
                # tables were computed against state just declared dead
                # (reset) or superseded (hot-swap).  Hand them to the
                # in-flight caller — its batch fails or retries through
                # the service's own reset path — but do NOT persist
                # them past the invalidation.
                pass
            else:
                self._entries[key] = _CacheEntry(
                    tables, tables_nbytes(tables), self.ticks.next())
                self._update_gauges()
                grew = True
        if grew and self._on_growth is not None:
            self._on_growth()  # registry budget sweep, outside our lock
        return tables

    def set_growth_hook(self, hook) -> None:
        self._on_growth = hook

    # -- the eviction side (driven by the registry) -------------------------

    def lru_entries(self) -> list[tuple[int, tuple, int]]:
        """``(stamp, key, bytes)`` per entry — the registry merges these
        with its residencies when the shared budget is exceeded."""
        with self._lock:
            return [(e.stamp, key, e.bytes)
                    for key, e in self._entries.items()]

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes for e in self._entries.values())

    def evict(self, key: tuple) -> int:
        """Drop one entry (budget eviction); returns the bytes freed
        (0 if the entry was already gone) so the registry's sweep can
        decrement its running total instead of re-scanning."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                return 0
            self._c_evictions.inc()
            self._update_gauges()
            return ent.bytes

    def invalidate_key(self, key_id: str) -> None:
        """Drop every generation/party/k entry for ``key_id`` — the
        generation-bump half of the shared invalidation hook
        (hot-swap / unregister / failure eviction)."""
        with self._lock:
            self._epoch += 1  # discard raced in-flight builds too
            victims = [k for k in self._entries if k[0] == key_id]
            for k in victims:
                del self._entries[k]
            if victims:
                self._c_evictions.inc(len(victims))
                self._update_gauges()

    def invalidate_all(self) -> None:
        """Drop everything (the shared ``reset_backend_health`` path —
        frontier state derived from a backend declared dead must not
        outlive it)."""
        with self._lock:
            self._epoch += 1  # discard raced in-flight builds too
            n = len(self._entries)
            self._entries.clear()
            if n:
                self._c_evictions.inc(n)
            self._update_gauges()

    # -- internals ----------------------------------------------------------

    def _update_gauges(self) -> None:  # caller holds the lock
        self._g_bytes.set(sum(e.bytes for e in self._entries.values()))
        self._g_entries.set(len(self._entries))

    def __repr__(self) -> str:
        with self._lock:
            return (f"FrontierCache(entries={len(self._entries)}, "
                    f"bytes={sum(e.bytes for e in self._entries.values())})")
