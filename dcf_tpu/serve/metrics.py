"""Dependency-free serving metrics: counters, gauges, histograms, and a
deterministic snapshot.

The observability contract of the serving layer (ADR: no prometheus/
opentelemetry dependency — the container bakes only the jax_graft
toolchain, and a metrics surface the tests can assert on exactly must be
deterministic anyway):

* ``Counter``   monotonically increasing float/int (requests, points,
  evictions, shed load, retries);
* ``Gauge``     last-written value (queue depth, resident device bytes);
* ``Histogram`` fixed-bound buckets + sum + count (stage/eval latency,
  batch occupancy, queue wait) — cumulative bucket counts in the
  snapshot, prometheus-style, so dashboards can be grafted on later
  without changing recording sites.

``Metrics.snapshot()`` returns a plain ``{name: value}`` dict with keys
in sorted order and only JSON-basic values, so a snapshot can be embedded
verbatim in a ``RESULTS_serve`` JSONL line and two snapshots diff
cleanly in tests.

Frontier-cache series (ISSUE 7, recorded by
``serve.frontier_cache.FrontierCache`` through this registry):
``serve_frontier_hits_total`` / ``serve_frontier_misses_total``
(cache consults — one per prefix-family eval dispatch plus the
stage-time warm), ``serve_frontier_evictions_total`` (budget +
invalidation), and the ``serve_frontier_cache_bytes`` /
``serve_frontier_cache_entries`` gauges.  Hit rate =
hits / (hits + misses); ``serve_bench --skew`` reports it per run.

Durability series (ISSUE 8, recorded by ``serve.store.KeyStore`` and
the warm-restart path): ``serve_store_writes_total`` /
``serve_store_deletes_total`` (durable publishes and removals),
``serve_store_quarantined_total`` (frames set aside typed at read
time), ``serve_store_restored_total`` (keys ``KeyRegistry.restore``
re-registered with their generations preserved), and the
``serve_store_keys`` gauge.  The hung-batch watchdog adds
``serve_batch_timeouts_total`` (batches failed typed with
``BatchTimeoutError`` for overrunning ``batch_timeout_s``).

Key-factory series (ISSUE 11, recorded by
``serve.keyfactory.KeyFactory``): ``keyfactory_pool_depth{pool=...}``
(per-pool gauge), ``keyfactory_pool_hits_total`` /
``keyfactory_pool_misses_total`` (claims: a miss is the counted
synchronous-mint fallback), ``keyfactory_minted_keys_total`` (DCF
keys minted, K-packed), ``keyfactory_published_total`` (pool frames
made durable — one manifest flip per refill batch),
``keyfactory_refills_total`` / ``keyfactory_refill_failures_total``,
``keyfactory_restored_total`` (entries re-pooled at warm restart),
``keyfactory_spent_reclaimed_total`` (claimed frames dropped by the
batched reclaim — durable claims reclaim atomically inside the
session frame's own publish flip instead) and
``keyfactory_worker_errors_total`` (refill-worker sweep failures that
escaped per-pool containment, e.g. a dying store's reclaim flip —
counted, never silently swallowed).  Pool-hit rate =
hits / (hits + misses); ``keyfactory_bench`` reports it per run.

Self-healing series (ISSUE 14, recorded by ``serve.health``,
``serve.replicate`` and the router): ``router_health_state{shard=}``
(0 up / 1 suspect / 2 down), ``router_probes_total{shard=}`` /
``router_probe_failures_total{shard=}``,
``router_health_transitions_total`` (+ ``{to=...}``),
``router_down_shards``, ``router_recover_gate_failures_total``,
``router_promoted_forwards_total`` (forwards served by a replica
promoted past a DOWN owner — the health plane's counterpart of
``router_failovers_total``, which stays the request-suspicion walk),
``router_down_refusals_total`` (every placed holder DOWN);
replication: ``router_registered_total`` /
``router_replicated_total`` / ``router_replicate_failures_total`` /
``router_replica_fenced_total``, ``router_anti_entropy_runs_total`` /
``router_anti_entropy_frames_total`` /
``router_anti_entropy_fenced_total``, and the shard-side
``serve_replica_applied_total`` / ``serve_replica_fenced_total``
(the monotonic-generation fence firing).  Host-churn hygiene: the
prober's and router's per-shard series are removed with the host
(``HealthProber.remove_target`` / ``DcfRouter.set_ring``), the
``BreakerBoard.forget`` discipline.

Membership series (ISSUE 15, recorded by ``serve.membership`` and the
epoch fence): ``membership_ejections_total`` /
``membership_joins_total`` / ``membership_drains_total`` (committed
ring changes), ``membership_migrated_frames_total`` (live frames the
convergence passes moved) /
``membership_durable_replications_total`` (``KeyStore.replicate_to``
copies), ``membership_change_failures_total`` (aborted changes —
retried on a later pump), ``membership_eject_skipped_total``
(min-hosts / multi-failure safety rails),
``membership_store_unreachable_total`` (stores skipped in a durable
pass because their digest read failed — a dead disk must not wedge
membership), ``membership_lost_keys_total``
(the zero-loss audit), ``membership_ring_size`` /
``membership_draining_hosts`` gauges; epoch planes:
``router_ring_epoch`` (the router's committed epoch) /
``router_stale_epoch_total`` (forwards refused because THIS router's
ring is stale), shard-side ``serve_ring_epoch`` (observed maximum) /
``serve_epoch_fenced_total`` (stale frames refused ``E_EPOCH``); the
store's ``serve_store_replicate_retries_total`` counts
``replicate_to``'s transient-``OSError`` retries.

Capacity series (ISSUE 16, recorded by ``serve.capacity``):
``capacity_ticks_total`` (control ticks) split into
``capacity_pressure_ticks_total`` / ``capacity_idle_ticks_total``
(verdicts — steady is the remainder), ``capacity_scale_out_total`` /
``capacity_scale_in_total`` (committed membership changes, which also
bump the ISSUE 15 join/drain series — the controller delegates),
``capacity_scale_failures_total`` (aborted changes, retried on a later
streak), ``capacity_forced_verdicts_total`` (the ``capacity.decide``
seam overriding a tick), and ``capacity_skips_total{reason=...}`` —
``cooldown`` / ``eject_inflight`` / ``min_hosts`` / ``max_hosts`` /
``no_standby`` / ``no_sample`` / ``frozen`` — every tick a scaling
decision was due but a safety rail said no; gauges:
``capacity_standby_hosts``, ``capacity_pressure_streak`` /
``capacity_idle_streak`` (the hysteresis positions), and the last
tick's aggregated ``capacity_queue_fraction`` /
``capacity_brownout_fraction``.

Secret hygiene: metric NAMES are static strings and metric values are
scalars; key ids chosen by callers become label values via ``labeled``
and must never be derived from key material (the dcflint secret-hygiene
pass also audits metric-sink call arguments, same rule as print/log).

Thread safety: one lock per ``Metrics`` registry guards every mutation
and the snapshot; instruments are cheap enough that a shared lock beats
per-instrument locks at serving rates (the device eval dwarfs both).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Sequence

__all__ = ["Counter", "Gauge", "Histogram", "Metrics", "labeled",
           "rollup_snapshots", "DEFAULT_LATENCY_BOUNDS",
           "OCCUPANCY_BOUNDS"]

#: Seconds buckets spanning sub-ms batching decisions to multi-second
#: CPU-mode large-batch evals.
DEFAULT_LATENCY_BOUNDS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)

#: Occupancy is a fraction in (0, 1]; padded batches land below 1.
OCCUPANCY_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def rollup_snapshots(snapshots) -> dict:
    """Sum per-host ``Metrics.snapshot()`` dicts into ONE pod view
    (ISSUE 13): counters and gauges add across hosts (a pod's resident
    bytes / queue depth / shed totals are the sums), histogram
    ``*_sum``/``*_count`` add, ``*_buckets`` add elementwise, and
    ``*_bounds`` must AGREE (same instrument definition on every host
    — a mismatch raises rather than summing apples onto oranges).
    Series only some hosts carry (per-tenant/per-key labels) sum over
    the hosts that have them.  Key order stays sorted — the rollup is
    itself a valid deterministic snapshot, so the pod benches embed it
    exactly like a single host's."""
    out: dict = {}
    for snap in snapshots:
        for name, value in snap.items():
            if name not in out:
                out[name] = (list(value) if isinstance(value, list)
                             else value)
            elif name.endswith("_bounds"):
                if list(value) != list(out[name]):
                    # api-edge: rollup contract — two hosts disagreeing
                    # on an instrument's bucket bounds is a deploy bug,
                    # not something to average away
                    raise ValueError(
                        f"histogram bounds differ across hosts for "
                        f"{name!r}")
            elif name.endswith("_buckets"):
                out[name] = [a + b for a, b in zip(out[name], value)]
            else:
                out[name] = out[name] + value
    return dict(sorted(out.items()))


def labeled(name: str, **labels: str) -> str:
    """Canonical ``name{k=v,...}`` metric-name form for labeled series
    (labels sorted, so the same label set is always the same series)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter; ``inc`` with a non-negative amount."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, amount: int | float = 1) -> None:
        if amount < 0:
            # api-edge: instrument-usage contract (programmer error)
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self.value += amount


class Gauge:
    """Last-written value."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def set(self, value: int | float) -> None:
        with self._lock:
            self.value = value

    def add(self, delta: int | float) -> None:
        with self._lock:
            self.value += delta


class Histogram:
    """Fixed-bound histogram: per-bucket counts (le semantics), sum,
    count.  Observations above the last bound land in the +Inf bucket."""

    __slots__ = ("_lock", "bounds", "buckets", "total", "count")

    def __init__(self, lock: threading.Lock,
                 bounds: Sequence[float] = DEFAULT_LATENCY_BOUNDS):
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            # api-edge: instrument-usage contract (programmer error)
            raise ValueError("histogram bounds must be strictly increasing")
        self._lock = lock
        self.bounds = tuple(float(b) for b in bounds)
        self.buckets = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        # bisect_left gives prometheus "le" placement: an observation
        # EQUAL to a bound belongs in that bound's bucket (occupancy 1.0
        # must land in le=1.0, not +Inf).
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self.buckets[idx] += 1
            self.total += value
            self.count += 1


class Metrics:
    """Registry of named instruments with a deterministic snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict = {}

    def _get(self, name: str, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            return inst

    def _typed(self, name: str, inst, want: type):
        if not isinstance(inst, want):
            # api-edge: instrument-usage contract (programmer error — one
            # name, one instrument kind)
            raise ValueError(f"metric {name!r} is already a "
                             f"{type(inst).__name__}, not a {want.__name__}")
        return inst

    def counter(self, name: str) -> Counter:
        return self._typed(name, self._get(
            name, lambda: Counter(self._lock)), Counter)

    def gauge(self, name: str) -> Gauge:
        return self._typed(name, self._get(
            name, lambda: Gauge(self._lock)), Gauge)

    def histogram(self, name: str,
                  bounds: Sequence[float] = DEFAULT_LATENCY_BOUNDS
                  ) -> Histogram:
        return self._typed(name, self._get(
            name, lambda: Histogram(self._lock, bounds)), Histogram)

    def remove(self, name: str) -> None:
        """Drop an instrument (and its snapshot series) by exact name.

        For bounded-cardinality hygiene on per-entity labeled series:
        a series keyed by a retired entity (e.g. an unregistered serve
        key's breaker-state gauge) would otherwise sit in every later
        snapshot forever — unbounded memory and snapshot bloat under
        entity churn.  Removing an absent name is a no-op."""
        with self._lock:
            self._instruments.pop(name, None)

    def snapshot(self) -> dict:
        """Point-in-time ``{name: value}`` with sorted keys and
        JSON-basic values only.  Counters/gauges map to their scalar;
        a histogram ``h`` expands to ``h_sum``, ``h_count``, and
        ``h_buckets`` (cumulative counts per ``h_bounds`` entry plus the
        trailing +Inf bucket)."""
        with self._lock:
            out: dict = {}
            for name in sorted(self._instruments):
                inst = self._instruments[name]
                if isinstance(inst, Histogram):
                    cum, acc = [], 0
                    for c in inst.buckets:
                        acc += c
                        cum.append(acc)
                    out[f"{name}_sum"] = round(inst.total, 9)
                    out[f"{name}_count"] = inst.count
                    out[f"{name}_bounds"] = list(inst.bounds)
                    out[f"{name}_buckets"] = cum
                else:
                    out[name] = inst.value
            # Key order is part of the determinism contract: expanded
            # histogram keys must land sorted too, not grouped.
            return dict(sorted(out.items()))
