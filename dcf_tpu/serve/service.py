"""DcfService: the online evaluator over the staged backends.

Turns a constructed ``Dcf`` facade into a service: callers ``submit``
``(key_id, xs)`` requests from any thread and get a ``ServeFuture``;
a worker coalesces requests into padded power-of-two device batches
(``serve.batcher``), keeps hot key images device-resident
(``serve.registry``), sheds overload at admission (``serve.admission``),
and reports itself through a deterministic metrics surface
(``serve.metrics``).

Load-bearing knobs (``ServeConfig``):

* ``max_batch`` — device batch cap in points, power of two.  The
  throughput knob: batches amortize the per-dispatch overhead, and every
  padded batch shape <= max_batch is one of log2(max_batch) compiled
  programs.  Raise it until eval latency, not dispatch overhead,
  dominates.
* ``max_delay_ms`` — the latency knob: how long an accepted request may
  wait for co-batching before the worker dispatches whatever is queued.
  The classic micro-batching latency/occupancy trade.
* ``device_bytes_budget`` — LRU bound on summed resident key images
  (0 = uncapped).  The working-set knob: more resident keys means fewer
  re-stagings; the budget is what stops a long tail of cold keys from
  evicting the hot set.  With ``frontier_cache`` on, serve-cached
  prefix frontiers share this budget (one merged LRU — see
  ``serve.frontier_cache``).
* ``frontier_cache`` — keep prefix-family frontier expansions in a
  serve-resident LRU (``serve.frontier_cache.FrontierCache``) keyed by
  (key_id, generation, party, k) instead of per backend instance, so a
  hot key's expanded top-k walk levels survive residency churn and
  re-staged instances skip the 2^k-node expansion entirely
  (``serve_frontier_hits_total`` / ``_misses_total`` /
  ``serve_frontier_cache_bytes`` in the snapshot).  Default on; only
  consulted by frontier-capable backends (``prefix``, ``hybrid`` with
  ``prefix_levels``) — everything else ignores it.  ``False`` restores
  the instance-store behavior (the cold leg ``serve_bench --skew``
  measures against).
* ``max_queued_points`` — admission bound; beyond it, submits shed with
  ``QueueFullError`` (see ``serve.admission``).
* ``retries`` — per-batch retries after a backend failure; each retry
  first runs the shared invalidation path (``Dcf.reset_backend_health``)
  so the retry re-stages on a freshly-selected backend instead of
  re-entering the dead one.
* ``breaker_failures`` / ``breaker_cooldown_s`` — the per-(key_id,
  backend-family) circuit breaker (``serve.breaker``): after
  ``breaker_failures`` consecutive failed ATTEMPTS (each failing
  dispatch and each failing retry records one — a batch failing
  outright with ``retries=1`` records two) the pairing opens and
  non-CRITICAL groups fail fast with ``CircuitOpenError`` instead of
  burning retries against a backend known to be dying; after the
  cooldown one probe half-opens it.  ``breaker_failures=0`` disables
  breakers entirely.
* ``brownout_queue_fraction`` / ``brownout_after_s`` /
  ``brownout_clear_s`` — the brownout controller: queue points above
  the fraction of ``max_queued_points`` for ``brownout_after_s``
  (or ANY open breaker, immediately) enters brownout — BATCH-class
  submits are refused at the door (``serve_brownout`` gauge = 1) —
  and ``brownout_clear_s`` of calm exits it (hysteresis: entry and
  exit are separated so a queue oscillating around the threshold does
  not flap the gate).
* ``store_dir`` — durable key store (ISSUE 8, ``serve.store``): a
  directory holding DCFK frames published write-fsync-rename under a
  CRC'd manifest.  ``register_key(..., durable=True)`` writes through
  BEFORE acking; after a crash, ``restore_keys()`` re-registers every
  durable key with its generation preserved (zero re-keygen — the
  offline phase is the expensive one) and quarantines damaged frames
  typed (``KeyQuarantinedError``) without failing the rest.  Empty
  (the default) = no store; ``durable=True`` then fails loudly.
* ``batch_timeout_s`` — the hung-batch watchdog: a wall deadline (on
  the injectable clock) each dispatched batch must complete within.
  An overdue batch fails typed (``BatchTimeoutError``), records a
  breaker outcome against the family it DISPATCHED on, and takes the
  same retry/invalidation path a plain batch failure takes — so a
  backend that wedges instead of crashing still demotes and still
  stops stalling the worker while the queue sheds behind it.  0 (the
  default) disables the watchdog.
* ``keyfactory_refill_interval_s`` — the key factory's worker-poll
  backstop (ISSUE 11, ``serve.keyfactory``): pools are refilled
  immediately when a claim drops them below their low-water mark (the
  claim nudges the worker), and at worst every this-many seconds.
  Declare pools with ``add_pool(PoolSpec(...))`` and mint fresh
  session keys with ``register_key(key_id, pool=...)`` — registration
  then costs a pool pop, not an n-level GGM keygen walk.
* ``tenants`` — the network edge's tenant table (ISSUE 12,
  ``serve.edge``): a tuple of ``admission.TenantSpec`` mapping each
  tenant onto the EXISTING priority classes (a frame may self-demote
  below its tenant class, never promote above it) and arming a
  per-tenant points-per-second token bucket on the injectable clock.
  Only the ``EdgeServer`` consults it; in-process submits are
  unaffected.  Empty (the default) = the open edge: every tenant
  serves as NORMAL, unlimited.

Pipelining: within a batch run, host->device staging of batch N+1
overlaps the (async) device eval of batch N — the worker dispatches
batch N, stages and dispatches N+1, and only then fetches N (the same
dispatch-ahead discipline bench.py uses, minus the RTT bookkeeping,
which belongs to the measurement layer).

Failure injection: the ``serve.stage`` / ``serve.eval`` seams
(``dcf_tpu.testing.faults``) fire at the exact points where a real
staging or dispatch failure would surface, so overload, mid-batch
backend death, and the retry/invalidation path are all deterministically
testable without breaking a real device.

Clocking: all waiting/deadline math uses the injectable ``clock``
(``utils.benchtime.monotonic`` by default) — never ``time.*`` directly;
the dcflint determinism pass enforces this, and deterministic tests
drive the service with a fake clock via ``pump()``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from dcf_tpu.errors import (
    BackendUnavailableError,
    BatchTimeoutError,
    CircuitOpenError,
    DeadlineExceededError,
    RingEpochError,
    ShapeError,
)
from dcf_tpu.protocols import ProtocolBundle
from dcf_tpu.protocols.combine import (
    combine_pair_shares,
    staged_pair_combine,
)
from dcf_tpu.utils.groups import np_group_add
from dcf_tpu.serve.admission import (
    AdmissionQueue,
    Priority,
    Request,
    ServeFuture,
    TenantSpec,
    expire,
    parse_priority,
)
from dcf_tpu.serve.breaker import BreakerBoard
from dcf_tpu.serve.batcher import (
    BatchPlan,
    gather_batch,
    ingest_points,
    plan_batches,
    scatter_batch,
)
from dcf_tpu.serve.frontier_cache import FrontierCache
from dcf_tpu.serve.keyfactory import KeyFactory, PoolSpec
from dcf_tpu.serve.metrics import Metrics, OCCUPANCY_BOUNDS
from dcf_tpu.serve.registry import KeyRegistry
from dcf_tpu.serve import replicate
from dcf_tpu.serve.store import KeyStore
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["ServeConfig", "DcfService"]


@dataclass(frozen=True)
class ServeConfig:
    """Serving policy; see the module docstring for which knobs are
    load-bearing and in which direction."""

    max_batch: int = 4096
    max_delay_ms: float = 2.0
    max_queued_points: int = 1 << 20
    device_bytes_budget: int = 0
    frontier_cache: bool = True
    retries: int = 1
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    brownout_queue_fraction: float = 0.75
    brownout_after_s: float = 0.5
    brownout_clear_s: float = 1.0
    store_dir: str = ""
    batch_timeout_s: float = 0.0
    keyfactory_refill_interval_s: float = 0.05
    tenants: tuple = ()
    tls_cert: str = ""
    tls_key: str = ""
    tls_client_ca: str = ""

    def __post_init__(self):
        # TLS on the edge socket (ISSUE 13 satellite): cert+key arm
        # the EdgeServer's ssl context; tls_client_ca pins clients
        # (router<->shard links).  Validated here so a half-configured
        # keypair dies at config time, not when the first EdgeServer
        # is constructed.
        if bool(self.tls_cert) != bool(self.tls_key):
            # api-edge: config contract — half a keypair serves nothing
            raise ValueError(
                "TLS needs BOTH tls_cert and tls_key (got only one)")
        if self.tls_client_ca and not self.tls_cert:
            # api-edge: config contract — client pinning needs a
            # server identity
            raise ValueError("tls_client_ca requires tls_cert/tls_key")
        for t in self.tenants:
            if not isinstance(t, TenantSpec):
                # api-edge: config contract — the tenant table is the
                # edge's admission policy; a loose dict would let a
                # typo'd field silently disable a tenant's rate limit
                raise ValueError(
                    f"tenants entries must be serve.TenantSpec, got "
                    f"{type(t).__name__}")
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            # api-edge: config contract — two specs for one tenant
            # would make the effective class/rate order-dependent
            raise ValueError(f"duplicate tenant names in {names}")
        if self.max_batch < 1 or self.max_batch & (self.max_batch - 1):
            raise ShapeError(
                f"max_batch must be a power of two >= 1, "
                f"got {self.max_batch}")
        if self.max_delay_ms < 0:
            # api-edge: config contract
            raise ValueError("max_delay_ms must be >= 0")
        if self.retries < 0:
            # api-edge: config contract
            raise ValueError("retries must be >= 0")
        if self.max_queued_points < 1:
            # api-edge: config contract (AdmissionQueue enforces the
            # same bound; failing here names the config field instead)
            raise ValueError(
                f"max_queued_points must be >= 1, "
                f"got {self.max_queued_points}")
        if self.device_bytes_budget < 0:
            # api-edge: config contract — a negative budget would read
            # as "always over budget" and silently evict everything
            raise ValueError(
                "device_bytes_budget must be >= 0 (0 = uncapped)")
        if self.breaker_failures < 0:
            # api-edge: config contract (0 disables the breakers)
            raise ValueError("breaker_failures must be >= 0")
        if self.breaker_cooldown_s < 0:
            # api-edge: config contract
            raise ValueError("breaker_cooldown_s must be >= 0")
        if not 0 < self.brownout_queue_fraction <= 1:
            # api-edge: config contract — 0 would make brownout
            # permanent, > 1 unreachable
            raise ValueError(
                "brownout_queue_fraction must be in (0, 1]")
        if self.brownout_after_s < 0 or self.brownout_clear_s < 0:
            # api-edge: config contract
            raise ValueError(
                "brownout_after_s/brownout_clear_s must be >= 0")
        if self.batch_timeout_s < 0:
            # api-edge: config contract (0 disables the watchdog)
            raise ValueError(
                f"batch_timeout_s must be >= 0, got {self.batch_timeout_s}")
        if self.keyfactory_refill_interval_s <= 0:
            # api-edge: config contract (the worker needs a finite,
            # positive poll backstop)
            raise ValueError(
                "keyfactory_refill_interval_s must be > 0, got "
                f"{self.keyfactory_refill_interval_s}")


class _Batch:
    """One in-flight batch: its plan, how to fetch its bytes, and the
    backend family it dispatched on (breaker outcomes are attributed to
    the family that RAN the batch — under dispatch-ahead a mid-group
    demotion must not charge an old batch's failure to the new family)."""

    __slots__ = ("plan", "fetch", "t0", "family")

    def __init__(self, plan: BatchPlan, fetch, t0: float,
                 family: str = ""):
        self.plan = plan
        self.fetch = fetch
        self.t0 = t0
        self.family = family


class DcfService:
    """Online DCF evaluation service over a ``Dcf`` facade.

    Construct via ``Dcf.serve(...)``.  Two driving modes:

    * ``start()`` spawns the worker thread (production / load tests);
      ``close(drain=True)`` stops admission, serves what is queued, and
      joins the worker.  The service is also a context manager.
    * ``pump()`` serves everything currently queued inline on the
      calling thread — the deterministic mode unit tests drive with a
      fake clock (no thread, no real time).
    """

    def __init__(self, dcf, config: ServeConfig | None = None, *,
                 metrics: Metrics | None = None, clock=monotonic):
        from dcf_tpu import api  # facade <-> serve wiring, cycle-free

        self._dcf = dcf
        self.config = config if config is not None else ServeConfig()
        self.metrics = metrics if metrics is not None else Metrics()
        self._clock = clock
        self.breakers = BreakerBoard(
            failures_to_open=max(self.config.breaker_failures, 1),
            cooldown_s=self.config.breaker_cooldown_s,
            metrics=self.metrics, clock=clock)
        self._breaker_enabled = self.config.breaker_failures > 0
        # Serve-resident frontier cache (ISSUE 7): prefix-family
        # frontier expansions keyed (key_id, generation, party, k),
        # sharing the registry's byte budget and LRU stamp sequence.
        self.frontier_cache = (FrontierCache(metrics=self.metrics)
                               if self.config.frontier_cache else None)
        self.registry = KeyRegistry(
            dcf.new_eval_backend,
            shared_image=dcf.backend_name == "keylanes",
            device_bytes_budget=self.config.device_bytes_budget,
            metrics=self.metrics, breakers=self.breakers,
            frontier_cache=self.frontier_cache)
        # Retry-after hints (ISSUE 12): overload sheds advise ~two
        # coalescing windows (the soonest a drained batch could have
        # made room — a heuristic, disclosed as such); brownout
        # refusals advise brownout_clear_s (the calm the hysteresis
        # controller needs before BATCH re-admits — the principled
        # lower bound on "when could this possibly succeed").
        self.queue = AdmissionQueue(
            self.config.max_queued_points, metrics=self.metrics,
            shed_retry_after_s=2 * self.config.max_delay_ms / 1e3,
            brownout_retry_after_s=self.config.brownout_clear_s)
        # Durable key store (ISSUE 8): the write-through target of
        # register_key(durable=True) and the source restore_keys()
        # re-registers from after a crash.
        self.store = (KeyStore(self.config.store_dir,
                               metrics=self.metrics)
                      if self.config.store_dir else None)
        if self.store is not None:
            # Floor the registry's generation counter on the store's
            # highest persisted generation BEFORE anything registers:
            # a fresh process on an existing store must never mint a
            # generation the manifest already records (the store's
            # monotonic put guard would silently drop that durable
            # write-through), and restore() preserving generations
            # stays exact either way.
            self.registry.sync_generation_floor(
                self.store.max_generation())
        # The key factory (ISSUE 11): ahead-of-demand keygen pools.
        # Inert until a pool is declared (``add_pool``); its refill
        # breakers live on its OWN board, so a dying keygen pipeline
        # never counts as serving-brownout pressure.  breaker_failures=0
        # disables only the SERVING breakers — refills keep the default
        # threshold (there is no un-gated mode for a background minter).
        self.keyfactory = KeyFactory(
            dcf, registry=self.registry, store=self.store,
            metrics=self.metrics, clock=clock,
            brownout=lambda: self.queue.brownout,
            refill_interval_s=self.config.keyfactory_refill_interval_s,
            breaker_failures=self.config.breaker_failures or 3,
            breaker_cooldown_s=self.config.breaker_cooldown_s)
        self._worker: threading.Thread | None = None
        self._pump_lock = threading.Lock()  # one batch runner at a time
        self._pump_owner: int | None = None  # thread id holding the lock
        # Brownout controller state (hysteresis timestamps on the
        # injectable clock; None = the condition is not currently held).
        # Guarded by _brownout_lock: _update_brownout runs on EVERY
        # submit (documented thread-safe) as well as in the pump, so
        # the check-then-subtract on these Optionals must be atomic.
        self._brownout_lock = threading.Lock()
        self._pressure_since: float | None = None
        self._calm_since: float | None = None
        # Ring-epoch fence state (ISSUE 15): the highest membership
        # epoch this shard has observed on a fenced frame; frames
        # carrying an older one are refused typed (check_ring_epoch).
        self._epoch_lock = threading.Lock()
        self._ring_epoch = 0
        # PIR answering context (ISSUE 20 satellite): None until
        # attach_pir; guarded-by: _pir_lock (the PirServer's selection
        # cache and evaluator residency are not themselves locked, and
        # edge reader threads submit concurrently).
        self._pir_lock = threading.Lock()
        self._pir = None
        m = self.metrics
        self._c_batches = m.counter("serve_batches_total")
        self._c_retries = m.counter("serve_retries_total")
        self._c_failures = m.counter("serve_batch_failures_total")
        self._c_breaker_fastfail = m.counter(
            "serve_breaker_fast_fails_total")
        self._c_batch_timeouts = m.counter("serve_batch_timeouts_total")
        self._c_deadline = m.counter("serve_deadline_expired_total")
        self._c_pir = m.counter("serve_pir_answers_total")
        self._c_epoch_fenced = m.counter("serve_epoch_fenced_total")
        self._g_ring_epoch = m.gauge("serve_ring_epoch")
        self._h_occupancy = m.histogram("serve_batch_occupancy",
                                        OCCUPANCY_BOUNDS)
        self._h_stage = m.histogram("serve_stage_s")
        self._h_eval = m.histogram("serve_eval_s")
        self._h_wait = m.histogram("serve_queue_wait_s")
        # The shared invalidation path: reset_backend_health() (module or
        # facade method) must evict this service's staged images too, so
        # a backend declared dead mid-serve cannot serve from cache.
        api.register_reset_listener(self)

    # -- invalidation -------------------------------------------------------

    def _on_backend_health_reset(self) -> None:
        self.registry.evict_all()
        if self.frontier_cache is not None:
            # evict_all already invalidated per registered key; this
            # sweeps anything else and bumps the cache epoch, so an
            # in-flight build that started before the reset cannot
            # persist its result — dead-backend state must not survive
            # the shared reset path anywhere.
            self.frontier_cache.invalidate_all()

    # -- key management -----------------------------------------------------

    def add_pool(self, spec: PoolSpec) -> PoolSpec:
        """Declare a key-factory pool (ISSUE 11, ``serve.keyfactory``)
        and start the refill worker if this service's worker is already
        running — fresh session keys then register via
        ``register_key(key_id, pool=spec.name)`` at pool-pop latency."""
        spec = self.keyfactory.add_pool(spec)
        if self._worker is not None and self._worker.is_alive():
            self.keyfactory.start()
        return spec

    def register_key(self, key_id: str, bundle=None,
                     durable: bool = False, *, pool: str | None = None):
        """Register (or hot-swap) the two-party bundle ``key_id`` serves.
        Swapping evicts the old device residencies atomically.  Returns
        the registered bundle (the ``ProtocolBundle`` for protocol
        keys).

        ``pool`` (ISSUE 11, with ``bundle=None``): mint a FRESH session
        key from the named key-factory pool instead of accepting a
        caller-generated bundle — the ahead-of-demand path.  A pool hit
        registers a pre-minted bundle (registration latency is a pool
        pop plus this method's bookkeeping, not a keygen walk), carrying
        the on-device staged narrow image into the registry when the
        factory minted one (zero host round-trip staging on the hybrid
        family).  Pool exhaustion falls back to a SYNCHRONOUS host mint
        on this call's clock — counted
        (``keyfactory_pool_misses_total``) and warned
        (``BackendFallbackWarning``), bit-exact in every observable
        (same function, fresh seeds), never silent.  The returned
        bundle is the dealer's copy: ship ``for_party(b)`` shares to
        the session's parties.

        ``bundle`` may be a plain ``KeyBundle`` OR a
        ``protocols.ProtocolBundle`` (PR 5): protocol keys serve MIC/
        IC/piecewise queries — the device ships the inner 2m-key image
        exactly like a plain key, and the service applies the
        per-interval share combine (+ the party's public-correction
        mask) when it fetches each batch, under the same admission/
        deadline/retry semantics.  Futures for a protocol key resolve
        to uint8 [m, M, lam] (per-interval shares) instead of
        [K, M, lam].

        Device-GENERATED bundles (``gen.gen_on_device`` /
        ``Dcf.gen(..., device=True)``, ISSUE 10) register exactly like
        host-generated ones: the pipelines are pinned byte-identical,
        so the registry, the staging backends and the durable store
        codecs see the same DCFK bytes either way — a keygen pipeline
        choice can never invalidate a stored frame.

        ``durable=True`` (ISSUE 8, needs ``store_dir``): the frame is
        written through to the durable store — atomic
        write-fsync-rename under the key's registry generation —
        BEFORE this call returns, so an acked durable registration
        survives a crash and ``restore_keys()`` brings it back with
        zero re-keygen.  If the persist raises (disk fault), the key
        IS live in the registry but NOT durable — the caller must
        treat the exception as "not persisted" and retry or
        re-register.  Hot-swapping a durable key with ``durable=False``
        deliberately leaves the previous durable snapshot in the store
        (durability is opt-in per write; a crash then restores the
        last DURABLE generation)."""
        dev_planes = None
        claimed_pool_id = ""
        if bundle is None:
            if pool is None:
                # api-edge: registration contract — either a bundle or
                # a pool to mint from, never neither
                raise ValueError(
                    f"register_key({key_id!r}) needs a bundle or a "
                    "pool= to mint a fresh session key from")
            minted = self.keyfactory.claim(pool)
            bundle = (minted.protocol if minted.protocol is not None
                      else minted.bundle)
            dev_planes = minted.planes
            claimed_pool_id = minted.pool_id  # "" for fallback mints
        elif pool is not None:
            # api-edge: registration contract (an explicit bundle and a
            # pool mint are different provenances; passing both hides
            # which one actually serves)
            raise ValueError(
                f"register_key({key_id!r}): pass a bundle OR pool=, "
                "not both")
        registered = bundle
        protocol = None
        if isinstance(bundle, ProtocolBundle):
            protocol, bundle = bundle, bundle.keys
        if bundle.lam != self._dcf.lam:
            raise ShapeError(
                f"bundle lam {bundle.lam} != service lam {self._dcf.lam}")
        if bundle.n_bits != 8 * self._dcf.n_bytes:
            raise ShapeError(
                f"bundle domain {bundle.n_bits} bits != service domain "
                f"{8 * self._dcf.n_bytes} bits")
        if durable and self.store is None:
            # api-edge: config contract — silently accepting a durable
            # registration with nowhere to persist it would be exactly
            # the data loss the flag exists to prevent
            raise ValueError(
                f"register_key({key_id!r}, durable=True) needs a "
                "configured store (ServeConfig.store_dir)")
        generation = self.registry.register(key_id, bundle,
                                            protocol=protocol,
                                            dev_planes=dev_planes)
        if durable:
            # A durable POOL claim folds the spent ~pool/ frame's
            # delete into the same manifest flip that publishes the
            # session frame: no crash window may leave both visible,
            # or a restore would re-pool key material a restored
            # session key already serves (cross-session reuse).  The
            # factory's lazy batched reclaim then finds the id gone —
            # a no-op.
            self.store.put(key_id, bundle, protocol=protocol,
                           generation=generation,
                           drop=(claimed_pool_id,) if claimed_pool_id
                           else ())
        return registered

    def unregister_key(self, key_id: str) -> None:
        """Forget ``key_id`` entirely: registry entry, residencies,
        breaker history — and its durable frame, when a store is
        configured (the name ceased to exist; restoring it after this
        would resurrect a key the operator deleted)."""
        self.registry.unregister(key_id)
        if self.store is not None:
            self.store.delete(key_id)

    def restore_keys(self):
        """Warm restart (ISSUE 8): re-register every key the durable
        store holds, preserving generations (zero re-keygen; damaged
        frames quarantined typed, never fatal to the rest — see
        ``KeyRegistry.restore``).  Restored ``~pool/...`` frames route
        back into their key-factory pools instead of the serving
        registry (ISSUE 11) — the report moves them from ``restored``
        to ``repooled``, generations preserved.  Returns the
        ``RestoreReport``."""
        if self.store is None:
            # api-edge: config contract (restore needs a store)
            raise ValueError(
                "restore_keys() needs a configured store "
                "(ServeConfig.store_dir)")
        report = self.registry.restore(self.store)
        self.keyfactory.adopt_restored(report, self.registry)
        return report

    def key_ids(self) -> list[str]:
        return self.registry.key_ids()

    # -- replication surface (ISSUE 14, ``serve.replicate``) ----------------

    def register_frame(self, key_id: str, frame,
                       proto: bool = False) -> int:
        """Register one DCFK frame off the wire (the OWNER half of the
        DCFE REGISTER verb): decode through the existing codec, mint a
        fresh generation, return it — the router forwards it to the
        replicas with this generation preserved.  Live (non-durable)
        by design: ``KeyStore.replicate_to`` is the durable twin."""
        obj = replicate.decode_key_frame(frame, proto)
        self.register_key(key_id, obj)
        return self.registry.snapshot(key_id)[2]

    def apply_replica_frame(self, key_id: str, frame, generation: int,
                            proto: bool = False) -> int:
        """Apply one forwarded frame under the owner's generation (the
        REPLICA half of REGISTER, and the anti-entropy apply).  The
        monotonic-generation fence refuses a frame at or below the
        local generation typed ``StaleStateError``
        (``serve_replica_fenced_total``) — an old partition side can
        never roll this key back."""
        return replicate.apply_frame(
            self.registry, key_id, frame, int(generation),
            bool(proto), lam=self._dcf.lam,
            n_bytes=self._dcf.n_bytes, metrics=self.metrics)

    def replication_digest(self) -> dict:
        """The live ``{key_id: generation}`` map (anti-entropy digest
        exchange — generations only, no key material)."""
        return self.registry.digest()

    # -- ring-epoch fence (ISSUE 15, ``serve.membership``) ------------------

    @property
    def ring_epoch(self) -> int:
        """The highest ring epoch this shard has observed (0 = never
        fenced — a solo service, or one no membership controller has
        touched)."""
        return self._ring_epoch

    def check_ring_epoch(self, epoch: int, adopt: bool = True) -> int:
        """Adopt-or-refuse one fenced frame's ring epoch (ISSUE 15).

        Monotonic-max adoption, the generation fence's discipline
        lifted to membership: a NEWER epoch is adopted (the first
        fenced frame after a membership commit teaches this shard the
        new epoch — probes disseminate it within about one interval),
        an EQUAL one passes, and an OLDER one is refused typed
        ``RingEpochError`` (``E_EPOCH`` on the wire, counted
        ``serve_epoch_fenced_total``) — a router still routing on a
        pre-change ring is structurally unable to serve or register
        against a conflicting placement.  Epoch 0 (unfenced) is a
        no-op pass.  Returns the current epoch.

        ``adopt=False`` runs the refuse-if-older half WITHOUT raising
        the observed maximum: the edge's REQUEST path checks the fence
        before tenant admission (a stale router must not burn a token
        on a structurally-refused forward) but must not let an
        UNADMITTED sender teach this shard an arbitrary epoch — one
        forged frame with a huge epoch would otherwise fence out the
        real router (adoption happens post-admission; PING/REGISTER
        stay adopt-on-sight — they are router/operator verbs under the
        TLS client-pinning trust story, not the tenant table)."""
        epoch = int(epoch)
        if epoch <= 0:
            return self._ring_epoch
        with self._epoch_lock:
            if epoch > self._ring_epoch:
                if adopt:
                    self._ring_epoch = epoch
                    self._g_ring_epoch.set(epoch)
            elif epoch < self._ring_epoch:
                self._c_epoch_fenced.inc()
                raise RingEpochError(
                    f"frame carries ring epoch {epoch} but this shard "
                    f"has observed epoch {self._ring_epoch}: the "
                    "sender's membership view is stale — refresh the "
                    "ring before retrying",
                    retry_after_s=1.0)
            return self._ring_epoch

    def sync_frames(self, digest: dict) -> list:
        """Frames STRICTLY newer than ``digest`` records, for the
        anti-entropy pull (``serve.replicate.sync_frames``)."""
        return replicate.sync_frames(self.registry, digest)

    # -- submission ---------------------------------------------------------

    @property
    def n_bytes(self) -> int:
        """The service's packed point width in bytes — the one shape
        fact every submit target shares (``EdgeClient`` carries it,
        the pod router carries its own), so the edge, the loadgen and
        the router read it without reaching into the facade."""
        return self._dcf.n_bytes

    def submit(self, key_id: str, xs: np.ndarray, b: int = 0,
               deadline_ms: float | None = None,
               priority: Priority | str = Priority.NORMAL) -> ServeFuture:
        """Submit points for one registered key, party ``b``.

        ``xs``: uint8 [M, n_bytes], M >= 1.  ``deadline_ms`` bounds the
        time the request may spend QUEUED; expiry completes the future
        with ``DeadlineExceededError``.  ``priority`` — CRITICAL /
        NORMAL (default) / BATCH — decides who is shed under overload
        and brownout, never dispatch order (``serve.admission``).
        Raises ``QueueFullError`` when shed.  Thread-safe.

        Normalizes ``xs`` and routes through :meth:`submit_bytes` —
        the batcher has exactly ONE feed (``batcher.ingest_points``),
        shared with the network edge's wire path (ISSUE 12)."""
        xs = np.ascontiguousarray(np.asarray(xs, dtype=np.uint8))
        if xs.ndim != 2 or xs.shape[1] != self._dcf.n_bytes:
            raise ShapeError(
                f"xs must be [M, {self._dcf.n_bytes}], got {xs.shape}")
        if xs.shape[0] < 1:
            raise ShapeError("cannot submit an empty request")
        return self.submit_bytes(key_id, xs.data, b=b,
                                 deadline_ms=deadline_ms,
                                 priority=priority)

    def submit_bytes(self, key_id: str, data, b: int = 0,
                     deadline_ms: float | None = None,
                     priority: Priority | str = Priority.NORMAL
                     ) -> ServeFuture:
        """Submit packed point BYTES for one registered key (ISSUE 12).

        ``data``: any buffer-protocol object holding M >= 1 points of
        ``n_bytes`` each, back to back — the network edge hands the
        received frame's payload ``memoryview`` straight here, and
        ``submit`` hands its normalized ndarray's buffer, so EVERY
        request reaches the batcher through ``batcher.ingest_points``
        (zero copies, zero per-point Python objects; the first copy of
        wire bytes is the span gather into the padded device batch).
        The caller must not mutate ``data`` until the future completes.
        Same admission/deadline/priority semantics as ``submit``."""
        if b not in (0, 1):
            # api-edge: party index contract at the serve edge
            raise ValueError(f"party b must be 0 or 1, got {b}")
        priority = parse_priority(priority)
        xs = ingest_points(data, self._dcf.n_bytes)
        from dcf_tpu.protocols.dpf import DpfBundle

        bundle = self.registry.bundle(key_id)  # unknown fails at submit
        if isinstance(bundle, DpfBundle):
            # A DPF registration is a PIR query: the KEY is the query
            # (full-domain EvalAll + database inner product), so the
            # request's points are a wire-contract placeholder — the
            # DCFE REQUEST frame needs M >= 1 — and the answer is
            # computed here, not batched (a PIR answer has no point
            # batch to coalesce with; PirServer carries its own
            # serve.eval retry-then-evict discipline).  Same wire both
            # ways: the [K, record_bytes] answer shares ride the SHARE
            # frame as [k=K, m=1, lam=record_bytes].
            return self._submit_pir(key_id, b)
        now = self._clock()
        self._update_brownout(now)  # the gate reflects current pressure
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        req = Request(key_id, b, xs, deadline, now, priority)
        self.queue.put(req)  # sheds with QueueFullError on overload
        return req.future

    def evaluate(self, key_id: str, xs: np.ndarray, b: int = 0,
                 deadline_ms: float | None = None,
                 timeout: float | None = None,
                 priority: Priority | str = Priority.NORMAL) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(key_id, xs, b, deadline_ms,
                           priority).result(timeout)

    # -- PIR (ISSUE 20 satellite) -------------------------------------------

    def attach_pir(self, db, evaluator=None, *,
                   retries: int | None = None):
        """Attach a 2-server-PIR answering context to this service.

        ``db``: a ``workloads.pir.PirDatabase``.  ``evaluator``: a
        ``backends.evalall.DpfEvalAll`` (defaults to one built from the
        facade's lam/cipher keys, interpret mode off-TPU — the same
        rule every Pallas facade path applies).  After attaching, a
        request submitted against a registered ``DpfBundle`` — over the
        local API or the DCFE wire, including a pod router's two-hop
        forward — answers as a PIR query instead of a point batch.
        ``retries`` defaults to the service's per-batch retry budget.
        Returns the ``PirServer`` (its ``eval_faults`` counter is the
        fault-soak observable)."""
        from dcf_tpu.backends.evalall import DpfEvalAll
        from dcf_tpu.workloads.pir import PirServer

        if evaluator is None:
            import jax

            evaluator = DpfEvalAll(
                self._dcf.lam, self._dcf.cipher_keys,
                interpret=jax.devices()[0].platform != "tpu")
        with self._pir_lock:
            self._pir = PirServer(
                evaluator, db, self.registry,
                retries=self.config.retries if retries is None
                else retries)
        return self._pir

    def _submit_pir(self, key_id: str, b: int) -> ServeFuture:
        """One PIR answer as a completed ``ServeFuture`` (see
        ``submit_bytes``: the key is the query, so there is nothing to
        queue — the EvalAll + inner product run at submit, under the
        PirServer's own retry-then-evict discipline)."""
        if self._pir is None:
            # api-edge: documented serving contract — a DPF key is
            # servable only once the database context exists
            raise ShapeError(
                f"key {key_id!r} is a DPF (PIR) registration but no "
                "database is attached to this service — call "
                "attach_pir(db) first")
        fut = ServeFuture()
        try:
            with self._pir_lock:
                ans = self._pir.answer(key_id, b)
        except Exception as e:  # fallback-ok: retries exhausted inside
            # PirServer — the typed cause completes the future, same
            # contract as a failed point batch
            fut.set_exception(e)
            return fut
        self._c_pir.inc()
        fut.set_result(ans[:, None, :])  # [K, 1, record_bytes] planes
        return fut

    # -- serving ------------------------------------------------------------

    # -- resilience (breaker + brownout) ------------------------------------

    def _record_outcome(self, key_id: str, family: str,
                        ok: bool) -> None:
        """Feed one batch attempt's outcome to the breaker board, keyed
        by the family the attempt DISPATCHED on (captured at dispatch
        time and threaded through ``_Batch`` — not re-read, so under
        dispatch-ahead a batch dispatched pre-demotion still charges
        its late fetch failure to the family that earned it).  After a
        final-retry ``reset_backend_health`` demotion the next
        attempt's outcome belongs to the NEW family — a fresh breaker,
        born closed."""
        if not self._breaker_enabled:
            return
        if ok:
            self.breakers.record_success(key_id, family)
        else:
            self.breakers.record_failure(key_id, family)

    def _watchdog_check(self, batch: _Batch,
                        since: float | None = None) -> None:
        """The hung-batch watchdog (ISSUE 8): raise typed if ``batch``
        overran its wall deadline.  Called INSIDE the dispatch/fetch
        containment try blocks, so an overdue batch records a failure
        outcome against the family it dispatched on and takes the
        existing retry/invalidation path — a backend that wedges (eats
        the clock without erroring) degrades exactly like one that
        crashes, instead of stalling the worker forever while the queue
        sheds behind it.

        Two windows are judged SEPARATELY: the dispatch window
        (``batch.t0`` to dispatch-complete — a stage/eval call that ate
        the clock) and, via ``since``, the fetch wait on its own.  The
        pipeline overlap between them (batch N+1 staging while N is in
        flight) is deliberately charged to NEITHER: that time is the
        worker doing productive work, and charging it to batch N would
        spuriously fail a healthy batch whenever staging is slower than
        the timeout — double-burning device work on the retry.  Python
        cannot preempt a call that never returns; the watchdog's
        contract is that a slow call is judged against the injectable
        clock the moment it yields, which the ``latency`` fault seam
        makes deterministically testable."""
        timeout = self.config.batch_timeout_s
        if not timeout:
            return
        elapsed = self._clock() - (batch.t0 if since is None else since)
        if elapsed > timeout:
            self._c_batch_timeouts.inc()
            raise BatchTimeoutError(
                f"batch overran its wall deadline: {elapsed:.3f}s "
                f"elapsed > batch_timeout_s={timeout}s on backend "
                f"family {batch.family!r} — treating the dispatch as "
                "hung")

    def _expire_at_dispatch(self, group: list[Request], errors: dict,
                            pending) -> None:
        """Deadline enforcement at DISPATCH time (ISSUE 8 satellite):
        batch formation already expired what was overdue THEN, but a
        request can outlive its deadline while its batch sits in the
        dispatch-ahead slot behind a slow eval — burning a device eval
        on it would produce a share the caller already abandoned.
        Marks newly-expired requests failed (``DeadlineExceededError``
        through the group's error map, same counter as queue expiry);
        the plan loop then skips any batch whose every request is
        already failed.

        ``pending``: the request indices with spans in the current or a
        LATER plan.  A request whose evaluation already completed in
        earlier plans is never swept — failing it here would discard a
        finished result after its device work was burned, and make the
        outcome depend on what it happened to be co-batched with."""
        now = self._clock()
        for i in pending:
            if i not in errors and group[i].expired(now):
                errors[i] = DeadlineExceededError(
                    f"deadline passed in the dispatch-ahead slot "
                    f"({group[i]!r})")
                self._c_deadline.inc()

    def _update_brownout(self, now: float) -> None:
        """Enter/exit brownout with hysteresis (see the module
        docstring's knob table).  Open breakers enter IMMEDIATELY —
        the breaker's failure threshold already is a sustained-failure
        filter; queue-depth pressure must hold for ``brownout_after_s``
        first (one coalescing burst is not an overload).

        Runs on every ``submit`` (thread-safe) and pump iteration;
        ``_brownout_lock`` makes the check-then-subtract on the
        hysteresis timestamps atomic — a concurrent None-reset between
        the two would crash a submit with an untyped TypeError."""
        cfg = self.config
        # max(1, ...): int() truncates small bounds to a 0 threshold,
        # which an EMPTY queue satisfies — permanent brownout on an
        # idle service.
        depth_pressure = self.queue.points >= max(1, int(
            cfg.brownout_queue_fraction * cfg.max_queued_points))
        open_pressure = self._breaker_enabled and self.breakers.any_open()
        with self._brownout_lock:
            if open_pressure or depth_pressure:
                self._calm_since = None
                if open_pressure:
                    self.queue.set_brownout(True)
                    return
                if self._pressure_since is None:
                    self._pressure_since = now
                if now - self._pressure_since >= cfg.brownout_after_s:
                    self.queue.set_brownout(True)
                return
            self._pressure_since = None
            if not self.queue.brownout:
                return
            if self._calm_since is None:
                self._calm_since = now
            if now - self._calm_since >= cfg.brownout_clear_s:
                self.queue.set_brownout(False)
                self._calm_since = None

    def pump(self) -> int:
        """Serve everything queued right now, inline; returns the number
        of device batches dispatched.  The deterministic driving mode —
        also what the worker thread calls after its coalescing wait."""
        if self._pump_owner == threading.get_ident():
            # Reentrant pump (e.g. ``close`` called from a fault handler
            # or future callback INSIDE a running pump): the outer pump
            # already loops until the queue is empty, and re-acquiring
            # the non-reentrant lock here would deadlock the worker.
            return 0
        served = 0
        with self._pump_lock:
            self._pump_owner = threading.get_ident()
            try:
                while True:
                    now = self._clock()
                    self._update_brownout(now)
                    expire(self.queue.take_expired(now), self.metrics)
                    group = self.queue.take_group(self.config.max_batch)
                    if not group:
                        return served
                    try:
                        served += self._serve_group(group)
                    except Exception as e:  # fallback-ok: the worker must
                        # outlive ANY per-group failure (e.g. the key was
                        # unregistered between submit and dispatch) — fail
                        # the group's futures, keep serving other keys
                        for r in group:
                            if not r.future.done():
                                r.future.set_exception(e)
            finally:
                self._pump_owner = None

    def _serve_group(self, group: list[Request]) -> int:
        """Batch-evaluate one (key_id, party) group of requests."""
        now = self._clock()
        for r in group:
            self._h_wait.observe(max(now - r.enq_t, 0.0))
        key_id, b = group[0].key_id, group[0].b
        # The breaker gate: an open (key, backend-family) pairing fails
        # the whole group fast — pump's per-group containment delivers
        # the typed CircuitOpenError to every future — unless the group
        # carries CRITICAL traffic, which keeps its pre-breaker
        # semantics (dispatch + bounded retries) and doubles as the
        # recovery sensor once the half-open window arrives.
        fam = self._dcf.backend_name
        if self._breaker_enabled and not self.breakers.allow(
                key_id, fam,
                critical=any(r.priority is Priority.CRITICAL
                             for r in group)):
            self._c_breaker_fastfail.inc(len(group))
            raise CircuitOpenError(
                f"circuit breaker open for key {key_id!r} on backend "
                f"family {fam!r}: failing fast until the cooldown's "
                "half-open probe succeeds",
                retry_after_s=self.breakers.retry_after(key_id, fam))
        try:
            return self._serve_group_batches(group, key_id, b)
        except BaseException:  # fallback-ok: re-raised below — this
            # handler only sweeps orphaned board state on the way out,
            # it swallows nothing.
            # A NON-batch failure escaped (stale snapshot, key
            # unregistered between gate and dispatch — batch failures
            # are contained below and recorded).
            if self._breaker_enabled and (
                    key_id not in self.registry.key_ids()):
                # The gate's allow() above (re-)creates board state
                # for its pairing.  If the key was unregistered
                # between submit and dispatch, forget() already ran
                # and nothing will ever run it again — sweep the
                # orphan or the board leaks one entry per churned
                # key (the allow()-path twin of the record_*
                # resurrection guards).
                self.breakers.forget(key_id)
            raise
        finally:
            # Release the probe slot if the gate sanctioned this group
            # as the half-open probe but no batch outcome ever resolved
            # it against THIS family — the prober died pre-dispatch
            # (non-batch failure above), or a concurrent
            # reset_backend_health() demotion re-pointed the facade
            # between the gate and the dispatch so every outcome was
            # recorded against the NEW family (_Batch.family).  A
            # resolved probe has left HALF_OPEN, making this a no-op;
            # a wedged one would otherwise fail (key, fam) fast
            # forever with no recovery path short of unregistering.
            if self._breaker_enabled:
                self.breakers.abort_probe(key_id, fam)

    def _serve_group_batches(self, group: list[Request], key_id: str,
                             b: int) -> int:
        # ONE locked read: a concurrent register() hot-swap must never
        # pair this bundle's geometry (or combine masks) with a
        # different entry's state; the generation travels with the
        # snapshot so resident() refuses to re-stage a swapped key under
        # this group.
        snap = self.registry.snapshot(key_id)
        bundle, proto, _ = snap
        k_num, lam = bundle.num_keys, bundle.lam
        if proto is not None:
            k_num = proto.num_intervals  # batches fetch combined rows
        xs_list = [r.xs for r in group]
        outs = [np.empty((k_num, r.m, lam), dtype=np.uint8) for r in group]
        plans = plan_batches([r.m for r in group], self.config.max_batch)
        errors: dict[int, BaseException] = {}  # req index -> failure

        def finish(batch: _Batch, y: np.ndarray | None,
                   err: BaseException | None) -> None:
            if err is not None:
                self._c_failures.inc()
                for sp in batch.plan.spans:
                    errors.setdefault(sp.req, err)
                return
            self._h_eval.observe(max(self._clock() - batch.t0, 0.0))
            self._h_occupancy.observe(batch.plan.occupancy)
            scatter_batch(outs, batch.plan, y)

        # Dispatch-ahead pipeline: batch N+1 is staged and dispatched
        # while batch N's result is still in flight; N is fetched after.
        # last_plan: each request's final plan index, so the dispatch-
        # time deadline sweep only touches requests with work still
        # ahead of the current plan.
        last_plan: dict[int, int] = {}
        for pi, plan in enumerate(plans):
            for sp in plan.spans:
                last_plan[sp.req] = pi
        prev: _Batch | None = None
        dispatched = 0
        for pi, plan in enumerate(plans):
            self._expire_at_dispatch(
                group, errors,
                [i for i, last in last_plan.items() if last >= pi])
            if all(sp.req in errors for sp in plan.spans):
                # Every request this batch would evaluate has already
                # failed (deadline expired in the dispatch-ahead slot):
                # skip the eval outright — ``prev`` stays in flight and
                # completes on the next dispatched plan or after the
                # loop.
                continue
            dispatched += 1
            cur, y, err = self._run_batch(key_id, b, plan, xs_list, snap)
            if prev is not None:
                self._complete(prev, key_id, b, xs_list, finish, snap)
            if err is not None:
                finish(_Batch(plan, None, 0.0), None, err)
                prev = None
            elif y is not None:  # a sync retry already fetched its bytes
                finish(cur, y, None)
                prev = None
            else:
                prev = cur
        if prev is not None:
            self._complete(prev, key_id, b, xs_list, finish, snap)

        for i, r in enumerate(group):
            if i in errors:
                r.future.set_exception(errors[i])
            else:
                r.future.set_result(outs[i])
        return dispatched

    # -- batch execution ----------------------------------------------------

    def _run_batch(self, key_id: str, b: int, plan: BatchPlan, xs_list,
                   snap) -> tuple[_Batch | None, np.ndarray | None,
                                  BaseException | None]:
        """Dispatch one batch.  Returns (in-flight batch, None, None) on
        the happy path; (batch, bytes, None) when a failure forced the
        synchronous retry path (already fetched); (None, None, error)
        when retries were exhausted."""
        fam = self._dcf.backend_name  # the family this attempt runs on
        try:
            batch = self._dispatch(key_id, b, plan, xs_list, snap)
            self._watchdog_check(batch)  # a dispatch that ate the clock
            return batch, None, None
        except Exception as e:  # fallback-ok: ANY backend/seam failure
            # must be contained to this batch (retried or failed), never
            # allowed to kill the serve worker
            self._record_outcome(key_id, fam, ok=False)
            y, err = self._retry_sync(key_id, b, plan, xs_list, e, snap)
            if err is not None:
                return None, None, err
            return _Batch(plan, None, self._clock()), y, None

    def _dispatch(self, key_id: str, b: int, plan: BatchPlan,
                  xs_list, snap) -> _Batch:
        """Stage + dispatch one batch; returns the in-flight handle.

        ``snap``: the group's ``registry.snapshot`` — every batch of a
        group serves the same (bundle, protocol) pairing even across a
        concurrent re-register.  For protocol keys on staged backends
        whose plane layout is known, the pair-combine runs ON DEVICE at
        dispatch (``protocols.combine`` seam fires here; a failure takes
        the ``_run_batch`` retry path) and only [m, M, lam] converts to
        bytes — half the conversion volume.  Everywhere else the combine
        applies to the fetched bytes, so a combine failure takes the
        same retry/invalidation path as a backend failure, on both the
        pipelined and sync-retry paths."""
        t0 = self._clock()
        fam = self._dcf.backend_name  # attribution for breaker outcomes
        bundle, proto, generation = snap

        def wrap(fetch):
            if proto is None:
                return fetch
            masks = proto.masks_for(b)
            return lambda: np.asarray(
                combine_pair_shares(np.asarray(fetch()), masks,
                                    proto.group))

        xs_batch = gather_batch(xs_list, plan, self._dcf.n_bytes)
        fire("serve.stage", key_id, plan.m)
        # Host-path detection is DYNAMIC (resident() returns None when
        # the facade currently resolves to cpu/numpy): a mid-serve auto
        # fallback that lands on the numpy floor must serve through the
        # facade, not die on the device path it selected at construction.
        be = self.registry.resident(key_id, b, generation)
        if be is None:
            fire("serve.eval", key_id, plan.m)
            y = self._dcf.eval(b, bundle, xs_batch)
            self._c_batches.inc()
            return _Batch(plan, wrap(lambda: y), t0, fam)
        if hasattr(be, "stage"):
            staged = be.stage(xs_batch)
            self._h_stage.observe(max(self._clock() - t0, 0.0))
            fire("serve.eval", key_id, plan.m)
            y_dev = be.eval_staged(b, staged)  # async dispatch
            # Prefix-family backends build frontier tables on first
            # eval; re-measure so the LRU budget sees the real image.
            self.registry.note_image_growth(key_id, b)
            self._c_batches.inc()
            if proto is not None:
                # fires the seam
                y_comb = staged_pair_combine(be, y_dev, proto.group)
                if y_comb is not None:
                    masks = proto.masks_for(b)
                    return _Batch(
                        plan,
                        lambda: np_group_add(
                            be.staged_to_bytes(y_comb, plan.m),
                            masks[:, None, :], proto.group),
                        t0, fam)
            return _Batch(
                plan, wrap(lambda: be.staged_to_bytes(y_dev, plan.m)), t0,
                fam)
        fire("serve.eval", key_id, plan.m)
        y = be.eval(b, xs_batch)
        self._c_batches.inc()
        return _Batch(plan, wrap(lambda: y), t0, fam)

    def _complete(self, batch: _Batch, key_id: str, b: int, xs_list,
                  finish, snap) -> None:
        """Fetch an in-flight batch; a fetch-time failure (the dispatch
        is async — compile/execute errors can surface here) takes the
        same retry path as a dispatch-time one."""
        t_fetch = self._clock()  # the fetch WAIT is judged on its own:
        # time since dispatch includes batch N+1's staging (pipeline
        # overlap — productive, not a stall) and must not count
        try:
            y = batch.fetch()
            self._watchdog_check(batch, since=t_fetch)
        except Exception as e:  # fallback-ok: ANY backend/seam failure
            # must be contained to this batch (retried or failed), never
            # allowed to kill the serve worker
            self._record_outcome(key_id, batch.family, ok=False)
            y, err = self._retry_sync(key_id, b, batch.plan, xs_list, e,
                                      snap)
            if err is not None:
                finish(batch, None, err)
            else:
                finish(_Batch(batch.plan, None, self._clock()), y, None)
            return
        self._record_outcome(key_id, batch.family, ok=True)
        finish(batch, y, None)

    def _retry_sync(self, key_id: str, b: int, plan: BatchPlan, xs_list,
                    first: BaseException, snap
                    ) -> tuple[np.ndarray | None, BaseException | None]:
        """Bounded synchronous retries after a batch failure, with
        escalating invalidation.

        Early attempts evict only the failed key's residency (cheap — a
        transient fault must not cost every OTHER hot key its staged
        image).  The FINAL attempt runs the SHARED invalidation path
        (``Dcf.reset_backend_health`` — which evicts this service's
        whole residency cache through the listener registration) so it
        re-selects a healthy backend and re-stages rather than
        re-entering the instance that just died (the ``pallas.lowering``
        regression scenario).  With the default ``retries=1`` the one
        retry IS the final attempt and takes the shared path."""
        last: BaseException = first
        for attempt in range(self.config.retries):
            self._c_retries.inc()
            if attempt < self.config.retries - 1:
                self.registry.evict_key(key_id)
            else:
                self._dcf.reset_backend_health()
            fam = self._dcf.backend_name  # post-invalidation family
            try:
                batch = self._dispatch(key_id, b, plan, xs_list, snap)
                y = batch.fetch()
                self._watchdog_check(batch)  # a wedged retry fails too
            except Exception as e:  # fallback-ok: retry loop boundary —
                # the last failure is reported to the affected requests
                self._record_outcome(key_id, fam, ok=False)
                last = e
                continue
            self._record_outcome(key_id, fam, ok=True)
            return y, None
        return None, last

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DcfService":
        """Spawn the worker thread (idempotent), and the key factory's
        refill worker when pools are declared."""
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="dcf-serve", daemon=True)
            self._worker.start()
        if self.keyfactory.pool_names():
            self.keyfactory.start()
        return self

    def _worker_loop(self) -> None:
        max_delay = self.config.max_delay_ms / 1e3
        q = self.queue
        while True:
            with q.cond:
                while not len(q) and not q.closed:
                    q.cond.wait(timeout=0.1)
                if not len(q) and q.closed:
                    return
                # Coalescing wait: give co-batchable traffic max_delay to
                # arrive, unless a full batch is already queued or we are
                # draining (queue closed).
                while not q.closed and q.points < self.config.max_batch:
                    oldest = q.oldest_enq_t()
                    if oldest is None:
                        break
                    remaining = max_delay - (self._clock() - oldest)
                    if remaining <= 0:
                        break
                    q.cond.wait(timeout=remaining)
            self.pump()

    def close(self, drain: bool = True, timeout: float | None = None
              ) -> None:
        """Stop admission and shut down.

        ``drain=True`` (default): queued requests are served before the
        worker exits.  ``drain=False``: queued requests complete with
        ``BackendUnavailableError``.  Joins the worker (unless called
        FROM it — a fault handler or chaos scenario closing the service
        mid-batch must not self-join), and never leaves a future
        hanging: queued requests are failed or drained here, and
        requests already taken for an in-flight group are completed by
        the pump that owns them (its retry loop is bounded, so the join
        is too)."""
        self.queue.close()
        # Stop refilling first: a factory minting into a closing
        # service is wasted device work (and its close flushes the
        # batched spent-frame reclaim while the store is still owned).
        # A FAILING flush (dying disk at shutdown) is deferred, not
        # propagated here: the futures below must be failed/drained
        # first — close()'s never-leave-a-future-hanging contract
        # outranks surfacing the reclaim error promptly.
        keyfactory_error: BaseException | None = None
        try:
            self.keyfactory.close()
        except Exception as e:  # fallback-ok: re-raised at the end of
            # close(), after every queued future has been completed
            keyfactory_error = e
        if not drain:
            self.queue.fail_all(lambda: BackendUnavailableError(
                "service closed without draining"))
        worker = self._worker
        if worker is not None and worker.is_alive():
            if worker is not threading.current_thread():
                worker.join(timeout)
        else:
            self.pump()  # no worker: drain inline
        if drain:
            self.pump()  # belt-and-braces: nothing may stay queued
        if keyfactory_error is not None:
            raise keyfactory_error

    def __enter__(self) -> "DcfService":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close(drain=True)
        return False

    # -- observability ------------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """Deterministic point-in-time metrics dict (see serve.metrics)."""
        return self.metrics.snapshot()

    def load_report(self):
        """This shard's demand signals as one ``edge.LoadSample``
        (ISSUE 16): the capacity controller's per-shard input, served
        over the PING/PONG round trip (a ``want_load`` probe's PONG
        appends it — see ``serve.edge``).  Queue points and the
        brownout latch are instantaneous; the shed / tenant-refusal /
        key-factory-pool-miss fields are the CUMULATIVE counters (the
        controller differences consecutive samples).  Cheap by design:
        reads three existing instruments, never snapshots."""
        from dcf_tpu.serve.edge import LoadSample

        # Refresh the brownout gate first: on a FULLY quiet service the
        # worker sits in its condvar wait and never pumps, so the latch
        # set during a surge would otherwise read "browned out" forever
        # — and the autoscaler could never see idle to scale back in.
        self._update_brownout(self._clock())
        m = self.metrics
        return LoadSample(
            queue_points=self.queue.points,
            queue_limit=self.config.max_queued_points,
            brownout=self.queue.brownout,
            shed_total=m.counter("serve_shed_total").value,
            refusals_total=m.counter("edge_refused_total").value,
            pool_misses=m.counter(
                "keyfactory_pool_misses_total").value)
