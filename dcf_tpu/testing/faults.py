"""Fault-injection harness: named failure points, armed per-test.

Production seams call ``fire(point, *args)`` at the exact spot where the
real failure would surface (a ``make`` exit != 0, a ``CDLL`` load error, a
Mosaic lowering exception, a device-provisioning error).  Unarmed, ``fire``
is a dict lookup and a return — zero cost on the serving path.  Armed via
the ``inject`` context manager, it runs the test's handler, which raises —
so every fallback edge and every typed error in ``dcf_tpu.errors`` can be
exercised deterministically under ``JAX_PLATFORMS=cpu``, no real toolchain
or accelerator failure required.

    from dcf_tpu.testing import faults

    with faults.inject("pallas.lowering"):
        Dcf(16, 16, keys, backend="auto")   # canary fails -> bitsliced

Handlers receive ``fire``'s positional args (e.g. the ``portable`` flag at
the native seams) and may raise conditionally:

    with faults.inject("native.build",
                       handler=faults.fail_unless(lambda portable: portable)):
        native.load()                        # AES-NI build fails, portable OK

``corrupt`` is the canonical DCFK byte-mutation helper for key-ingestion
tests (flip one byte, let the CRC catch it).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Callable

__all__ = [
    "POINTS",
    "InjectedFault",
    "fire",
    "is_armed",
    "inject",
    "fail_unless",
    "corrupt",
]


class InjectedFault(Exception):
    """The default exception raised by an armed fault point."""


#: The named seams production code exposes.  ``inject`` rejects unknown
#: names so a typo in a test fails loudly instead of silently not arming.
POINTS = (
    "native.build",     # make exit != 0            (native/__init__.build)
    "native.load",      # ctypes.CDLL load failure  (native/__init__.load)
    "pallas.lowering",  # Mosaic compile/lowering   (pallas backends' eval)
    "mesh.provision",   # device/mesh provisioning  (parallel.mesh.make_mesh)
    "serve.stage",      # host->device batch staging (serve/service.py;
    #                     handler args: key_id, batch_points)
    "serve.eval",       # staged batch dispatch      (serve/service.py;
    #                     handler args: key_id, batch_points)
    "protocols.combine",  # per-interval share combine (protocols/
    #                     combine.py — both the host-bytes and the
    #                     staged-device paths, and therefore every
    #                     protocol batch the serve layer fetches;
    #                     handler args: m_intervals, batch_points
    #                     (-1 on the device path, where the point count
    #                     is not yet materialized))
)

_ACTIVE: dict[str, Callable] = {}


def fire(point: str, *args) -> None:
    """Production seam: run the armed handler for ``point``, if any."""
    handler = _ACTIVE.get(point)
    if handler is not None:
        handler(*args)


def is_armed(point: str) -> bool:
    return point in _ACTIVE


def fail_unless(ok: Callable[..., bool],
                exc: BaseException | None = None) -> Callable:
    """Handler factory: raise unless ``ok(*fire_args)`` is true."""

    def handler(*args):
        if not ok(*args):
            raise exc if exc is not None else InjectedFault(
                f"injected fault (args={args!r})")

    return handler


@contextmanager
def inject(point: str, exc: BaseException | None = None,
           handler: Callable | None = None):
    """Arm ``point`` for the duration of the block.

    Default behaviour raises ``InjectedFault`` (or ``exc``) on every fire;
    pass ``handler`` for conditional failures.  Nested injections restore
    the previous handler on exit.
    """
    if point not in POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; known points: {POINTS}")
    if handler is None:
        e = exc if exc is not None else InjectedFault(
            f"injected fault at {point!r}")

        def handler(*_args):
            raise e

    prev = _ACTIVE.get(point)
    _ACTIVE[point] = handler
    try:
        yield
    finally:
        if prev is None:
            _ACTIVE.pop(point, None)
        else:
            _ACTIVE[point] = prev


def corrupt(data: bytes, offset: int, xor: int = 0x01) -> bytes:
    """Flip bit(s) of one byte — the canonical DCFK corruption mutator."""
    if not 0 <= offset < len(data):
        raise ValueError(f"offset {offset} outside frame of {len(data)} bytes")
    if not 1 <= xor <= 0xFF:
        raise ValueError("xor must flip at least one bit (1..255)")
    buf = bytearray(data)
    buf[offset] ^= xor
    return bytes(buf)
