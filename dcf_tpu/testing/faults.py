"""Fault-injection harness: named failure points, armed per-test.

Production seams call ``fire(point, *args)`` at the exact spot where the
real failure would surface (a ``make`` exit != 0, a ``CDLL`` load error, a
Mosaic lowering exception, a device-provisioning error).  Unarmed, ``fire``
is a dict lookup and a return — zero cost on the serving path.  Armed via
the ``inject`` context manager, it runs the test's handler, which raises —
so every fallback edge and every typed error in ``dcf_tpu.errors`` can be
exercised deterministically under ``JAX_PLATFORMS=cpu``, no real toolchain
or accelerator failure required.

    from dcf_tpu.testing import faults

    with faults.inject("pallas.lowering"):
        Dcf(16, 16, keys, backend="auto")   # canary fails -> bitsliced

Handlers receive ``fire``'s positional args (e.g. the ``portable`` flag at
the native seams) and may raise conditionally:

    with faults.inject("native.build",
                       handler=faults.fail_unless(lambda portable: portable)):
        native.load()                        # AES-NI build fails, portable OK

``corrupt`` is the canonical DCFK byte-mutation helper for key-ingestion
tests (flip one byte, let the CRC catch it).

Fault SCHEDULES (ISSUE 6): one-shot handlers cover "a batch failed";
the failure modes production sees are *windows* — a backend that dies
for N evals and then recovers, a flaky one that fails a seeded fraction
of the time, a slow one that eats deadline headroom without erroring.

* ``inject_schedule(point, window_evals=N)`` arms fail-N-then-recover:
  the first N fires raise, every later fire passes through.  Yields the
  ``Schedule`` so tests can assert exactly how many evals the window
  absorbed.
* ``flaky(rate, seed)`` is a handler factory failing a seeded-RNG
  fraction of fires — deterministic per seed, so a chaos scenario's
  exact failure pattern replays.
* ``latency(clock, seconds)`` is the injected-latency seam: each fire
  ADVANCES the injectable clock (``FakeClock.advance``) instead of
  sleeping, so deadline expiry under a slow backend is testable in
  microseconds of wall time.  Chain ``then=`` for slow-AND-failing.
  Armed at ``serve.eval`` it IS the slow-eval seam the hung-batch
  watchdog tests drive: advancing the clock past ``batch_timeout_s``
  at the dispatch fire is indistinguishable from a wedged backend.

Durable-store seams (ISSUE 8): ``store.write`` / ``store.manifest``
fire AFTER the temp file is written and fsynced but BEFORE the atomic
rename publishes it (handler args: key_id — the caller-chosen name,
never key material — and the temp path).  A raising handler models a
crash before the rename (the store keeps its previous consistent
state); ``torn_write(nbytes)`` is the partial-write handler factory —
it truncates the temp file and lets the rename proceed, so a torn
frame lands DURABLY on disk, exactly what a power cut mid-flush leaves
behind for the quarantine machinery to find at restore.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Callable

__all__ = [
    "POINTS",
    "InjectedFault",
    "fire",
    "is_armed",
    "inject",
    "fail_unless",
    "corrupt",
    "FakeClock",
    "Schedule",
    "inject_schedule",
    "flaky",
    "latency",
    "partition",
    "torn_write",
]


class InjectedFault(Exception):
    """The default exception raised by an armed fault point."""


#: The named seams production code exposes.  ``inject`` rejects unknown
#: names so a typo in a test fails loudly instead of silently not arming.
POINTS = (
    "native.build",     # make exit != 0            (native/__init__.build)
    "native.load",      # ctypes.CDLL load failure  (native/__init__.load)
    "pallas.lowering",  # Mosaic compile/lowering   (pallas backends' eval)
    "mesh.provision",   # device/mesh provisioning  (parallel.mesh.make_mesh)
    "serve.stage",      # host->device batch staging (serve/service.py;
    #                     handler args: key_id, batch_points)
    "serve.eval",       # staged batch dispatch      (serve/service.py;
    #                     handler args: key_id, batch_points)
    "protocols.combine",  # per-interval share combine (protocols/
    #                     combine.py — both the host-bytes and the
    #                     staged-device paths, and therefore every
    #                     protocol batch the serve layer fetches;
    #                     handler args: m_intervals, batch_points
    #                     (-1 on the device path, where the point count
    #                     is not yet materialized))
    "store.write",      # durable key-frame publish (serve/store.py —
    #                     fires after write+fsync of the temp file,
    #                     before the atomic rename; handler args:
    #                     key_id, tmp_path.  Raise = crash pre-rename;
    #                     torn_write = partial write made durable)
    "store.manifest",   # manifest publish (serve/store.py — same
    #                     write-fsync-rename seam for the CRC'd
    #                     manifest; handler args: "", tmp_path)
    "keygen.device",    # on-device keygen walk (gen.gen_on_device —
    #                     fires before the device pipeline is touched;
    #                     handler args: num_keys, lam.  A raising
    #                     handler models a dead kernel/driver: the
    #                     router must fall back to the host gen_batch
    #                     silent-correct, counted by
    #                     gen.device_fallback_count, warned via
    #                     BackendFallbackWarning)
    "keyfactory.refill",  # key-factory pool refill (serve/keyfactory.py
    #                     — fires at the start of one refill batch,
    #                     before any key is minted; handler args:
    #                     pool_name, batch_count.  A raising handler
    #                     models a dead keygen pipeline: the refill
    #                     fails contained (counted, the worker
    #                     survives), repeated failures open the
    #                     factory's per-pool breaker, and claims serve
    #                     from the remaining pool / the counted
    #                     synchronous-mint fallback)
    "edge.accept",      # network-edge accept loop (serve/edge.py —
    #                     fires before each accept(); no handler args.
    #                     A raising handler models a transient accept
    #                     failure (EMFILE, a dying NIC): the loop must
    #                     count it (edge_accept_errors_total) and keep
    #                     accepting — live connections are untouched)
    "edge.read",        # network-edge connection read (serve/edge.py —
    #                     fires before each socket recv on a
    #                     connection; handler args: peer tag, bytes
    #                     wanted.  A raising handler models a dead/
    #                     malicious peer: the CONNECTION dies typed,
    #                     the accept loop and every other connection
    #                     survive.  ``latency(clock, s)`` here is the
    #                     slow-client seam: each blocking read advances
    #                     the injectable clock, so a stalled sender
    #                     demonstrably trips the existing deadline/
    #                     watchdog path instead of wedging the worker)
    "membership.migrate",  # ring-membership migration pass
    #                     (serve/membership.py — fires at the start of
    #                     each live-convergence pass of an eject/join/
    #                     drain, before any frame moves; handler args:
    #                     sorted target host ids, new ring size.  A
    #                     raising handler models a migration source
    #                     dying mid-change: the change ABORTS typed and
    #                     counted (membership_change_failures_total),
    #                     the ring stays on its last committed epoch,
    #                     and the controller retries on a later pump —
    #                     never a half-migrated commit)
    "capacity.decide",  # capacity-controller verdict seam
    #                     (serve/capacity.py — fires once per control
    #                     tick, after the signals are aggregated and
    #                     the verdict computed but before the
    #                     hysteresis/scaling act on it; handler args:
    #                     verdict kind ("pressure"/"idle"/"steady"),
    #                     the typed CapacityVerdict.  A handler raising
    #                     ``capacity.ForcedVerdict(kind)`` FORCES that
    #                     kind for the tick — how the surge bench's
    #                     oscillation leg scripts load walks without
    #                     timing games; ANY OTHER raise FREEZES the
    #                     tick: no streak advance, no scaling, counted
    #                     capacity_skips_total{reason=frozen} — the
    #                     operator's emergency brake)
    "mesh.collective",  # pod mesh co-evaluate dispatch (serve/router.py
    #                     — fires at the start of each co-evaluated
    #                     batch, after the dispatch-policy decision but
    #                     before any slice is scattered to a worker;
    #                     handler args: batch points, worker count.  A
    #                     raising handler models a dead mesh (a
    #                     collective that cannot form): the router must
    #                     degrade the batch to route-mode — counted
    #                     router_mesh_degraded_total, warned via
    #                     BackendFallbackWarning, zero lost keys — or,
    #                     when the caller FORCED co-evaluation, refuse
    #                     typed with MeshUnavailableError; never a bare
    #                     crash)
    "net.partition",    # pod network partition (serve/edge.py — fires
    #                     before each EdgeClient dial and each frame
    #                     send on a TAGGED client (the pod router tags
    #                     its shard links); handler args: local tag,
    #                     peer tag.  ``partition({...})`` is the
    #                     canonical handler: it raises OSError for the
    #                     named host pairs, which the edge client
    #                     contains as transport death — exactly what a
    #                     dropped/denied frame looks like to the
    #                     routing tier, so suspicion, health probing,
    #                     promotion and anti-entropy recovery are all
    #                     deterministically drivable without touching
    #                     a real network)
)

_ACTIVE: dict[str, Callable] = {}


def fire(point: str, *args) -> None:
    """Production seam: run the armed handler for ``point``, if any."""
    handler = _ACTIVE.get(point)
    if handler is not None:
        handler(*args)


def is_armed(point: str) -> bool:
    return point in _ACTIVE


def fail_unless(ok: Callable[..., bool],
                exc: BaseException | None = None) -> Callable:
    """Handler factory: raise unless ``ok(*fire_args)`` is true."""

    def handler(*args):
        if not ok(*args):
            raise exc if exc is not None else InjectedFault(
                f"injected fault (args={args!r})")

    return handler


@contextmanager
def inject(point: str, exc: BaseException | None = None,
           handler: Callable | None = None):
    """Arm ``point`` for the duration of the block.

    Default behaviour raises ``InjectedFault`` (or ``exc``) on every fire;
    pass ``handler`` for conditional failures.  Nested injections restore
    the previous handler on exit.
    """
    if point not in POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; known points: {POINTS}")
    if handler is None:
        e = exc if exc is not None else InjectedFault(
            f"injected fault at {point!r}")

        def handler(*_args):
            raise e

    prev = _ACTIVE.get(point)
    _ACTIVE[point] = handler
    try:
        yield
    finally:
        if prev is None:
            _ACTIVE.pop(point, None)
        else:
            _ACTIVE[point] = prev


class FakeClock:
    """Deterministic injectable clock (seconds).  The canonical fake for
    every ``clock=``-taking serve component; ``latency`` advances it to
    model a slow backend without sleeping."""

    def __init__(self, t: float = 1000.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"a monotonic clock cannot go back ({dt})")
        self.t += dt


class Schedule:
    """Fail-window handler state: raises for the first ``window_evals``
    fires, passes through after — the sustained-then-recovered failure
    one-shot handlers cannot express.  ``fired``/``failed`` expose how
    much of the window a scenario actually consumed."""

    def __init__(self, window_evals: int, exc: BaseException | None = None):
        if window_evals < 0:
            raise ValueError(
                f"window_evals must be >= 0, got {window_evals}")
        self.window_evals = int(window_evals)
        self.exc = exc
        self.fired = 0
        self.failed = 0

    @property
    def recovered(self) -> bool:
        """Has the failure window been fully consumed?"""
        return self.failed >= self.window_evals

    def __call__(self, *args) -> None:
        self.fired += 1
        if self.failed < self.window_evals:
            self.failed += 1
            raise self.exc if self.exc is not None else InjectedFault(
                f"injected fault {self.failed}/{self.window_evals} "
                f"of the scheduled window (args={args!r})")


@contextmanager
def inject_schedule(point: str, *, window_evals: int,
                    exc: BaseException | None = None):
    """Arm ``point`` with a fail-``window_evals``-then-recover schedule;
    yields the ``Schedule`` for fire/fail-count assertions."""
    sched = Schedule(window_evals, exc)
    with inject(point, handler=sched):
        yield sched


def flaky(rate: float, seed: int,
          exc: BaseException | None = None) -> Callable:
    """Handler factory: fail a seeded-RNG ``rate`` fraction of fires.
    Deterministic per ``(rate, seed)`` — reruns replay the exact same
    failure pattern, so chaos assertions can be exact."""
    import numpy as np

    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"rate must be in [0, 1], got {rate}")
    rng = np.random.default_rng(seed)

    def handler(*args):
        if rng.random() < rate:
            raise exc if exc is not None else InjectedFault(
                f"injected flaky fault (rate={rate}, args={args!r})")

    return handler


def latency(clock: FakeClock, seconds: float,
            then: Callable | None = None) -> Callable:
    """Handler factory: each fire advances the injectable ``clock`` by
    ``seconds`` — the slow-backend seam.  No sleep is involved: deadline
    expiry and brownout hysteresis react to the CLOCK, so advancing it
    is indistinguishable from the eval actually taking that long.
    ``then`` chains another handler (e.g. a ``Schedule``) after the
    advance for slow-AND-failing backends."""
    if seconds < 0:
        raise ValueError(f"latency must be >= 0, got {seconds}")

    def handler(*args):
        clock.advance(seconds)
        if then is not None:
            then(*args)

    return handler


def torn_write(nbytes: int) -> Callable:
    """Handler factory for the ``store.write``/``store.manifest`` seams:
    truncate the not-yet-renamed temp file to ``nbytes`` and RETURN, so
    the atomic rename proceeds and the torn frame becomes durable — the
    on-disk state a power cut mid-flush (or an fsync that lied) leaves
    behind.  The quarantine path, not the writer, must absorb it."""
    if nbytes < 0:
        raise ValueError(f"nbytes must be >= 0, got {nbytes}")

    def handler(_key_id, path, *_args):
        with open(path, "r+b") as fh:
            fh.truncate(nbytes)

    return handler


def partition(pairs, *, clock: Callable[[], float] | None = None,
              window: tuple[float, float] | None = None) -> Callable:
    """Handler factory for the ``net.partition`` seam (ISSUE 14): deny
    every frame between the named host pairs.  ``pairs`` is an iterable
    of ``(a, b)`` tag pairs, symmetric — ``("router", "shard-0")`` cuts
    both directions of that link.  The handler raises ``OSError`` (what
    a dropped frame looks like to a socket client), which the edge
    client contains as transport death: pending futures fail typed,
    the routing tier marks the peer suspect, health probes start
    failing — the partition is observable only through the same typed
    taxonomy a real one would produce.

    ``clock`` + ``window=(start, end)``: deny only while
    ``start <= clock() < end`` — the healable-partition window the
    partition/flap soaks drive (heal = the clock leaving the window;
    no un-arming race with in-flight requests)."""
    cut = {frozenset(p) for p in pairs}
    if any(len(p) != 2 for p in cut):
        raise ValueError(f"partition pairs must name two hosts: {pairs}")
    if (clock is None) != (window is None):
        raise ValueError("clock and window arm the healable window "
                         "together (pass both or neither)")

    def handler(src: str, dst: str, *_args) -> None:
        if frozenset((src, dst)) not in cut:
            return
        if window is not None:
            now = clock()
            if not window[0] <= now < window[1]:
                return
        raise OSError(
            f"injected network partition: {src!r} <-> {dst!r} is cut")

    return handler


def corrupt(data: bytes, offset: int, xor: int = 0x01) -> bytes:
    """Flip bit(s) of one byte — the canonical DCFK corruption mutator."""
    if not 0 <= offset < len(data):
        raise ValueError(f"offset {offset} outside frame of {len(data)} bytes")
    if not 1 <= xor <= 0xFF:
        raise ValueError("xor must flip at least one bit (1..255)")
    buf = bytearray(data)
    buf[offset] ^= xor
    return bytes(buf)
