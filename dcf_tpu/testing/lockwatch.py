"""TSan-lite lock-order watchdog (ISSUE 17's dynamic half).

The guarded-by and blocking-under-lock dcflint passes prove the
LEXICAL discipline: annotated state is touched under its lock, and no
I/O runs inside a critical section.  What no static pass can prove is
the ORDER two locks are taken in across threads — the classic
inversion (thread 1: A then B; thread 2: B then A) deadlocks only
under the right interleave, which is why it survives review and
every test that doesn't hit the window.  This module detects the
inversion WITHOUT needing the interleave, the way lockdep/TSan do:

* every lock created while the harness is armed is wrapped; each
  thread carries a stack of the watched locks it currently holds;
* a blocking acquire first records one directed edge ``held -> new``
  per currently-held lock into a global lock-order graph (the stack
  of the FIRST observation is kept per edge, so reports name real
  code, not the harness);
* an edge that would close a cycle raises a typed ``LockOrderError``
  — naming the cycle and where each edge was first observed —
  *before* the acquire blocks.  The detector fails fast with a
  readable report instead of reproducing the hang; one run of each
  code path suffices, no lucky timing required.

Identity is PER LOCK INSTANCE (two ``TokenBucket``\\ s' locks are
distinct nodes), so independent same-class locks never alias into
false cycles; the node name still carries the allocation site
(``file:line``) so reports read like code.  Non-blocking
(``blocking=False``) and timeout-bounded acquires update the held
stack but neither record edges nor raise — a try-lock or bounded wait
cannot deadlock, and flagging it would punish legitimate
lock-avoidance patterns.  Reentrant ``RLock`` re-acquires are depth
counted, not re-recorded.

Usage — the ``lockwatch`` pytest marker arms it per test (see
``tests/conftest.py``), and the chaos/soak serial CI legs run with it
armed so every lock order those suites exercise is continuously
proven acyclic::

    watch = lockwatch.arm()      # patches threading.Lock/RLock
    try:
        ...                      # run the threaded scenario
    finally:
        lockwatch.disarm(watch)  # restores; graph dies with watch

Only locks CREATED while armed are watched (the serve classes build
their locks in ``__init__``, so constructing the system under test
inside the armed window covers it).  ``threading.Condition`` built on
a watched ``RLock`` works unmodified: the wrapper exposes the
``_is_owned`` / ``_release_save`` / ``_acquire_restore`` protocol,
and a condition wait re-runs the order check on re-acquire.
"""

from __future__ import annotations

import threading
import traceback

from dcf_tpu.errors import LockOrderError

__all__ = ["LockWatch", "WatchedLock", "WatchedRLock", "arm", "disarm"]

#: Frames kept per first-observation stack (enough to name the code
#: path without drowning the report in harness frames).
_STACK_LIMIT = 16


def _site() -> str:
    """Allocation site of the lock being constructed: the innermost
    frame outside this module and ``threading.py``."""
    for frame in reversed(traceback.extract_stack(limit=24)[:-2]):
        fn = frame.filename
        if not fn.endswith(("lockwatch.py", "threading.py")):
            return f"{fn.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


def _here() -> str:
    return "".join(traceback.format_stack(limit=_STACK_LIMIT)[:-2])


class LockWatch:
    """One armed session's lock-order graph.

    Nodes are watched-lock instances (by construction sequence
    number); edges ``a -> b`` mean "some thread held ``a`` while
    blocking-acquiring ``b``", stamped with the stack of the first
    observation.  ``check_acquire`` is called by the wrappers before
    every blocking acquire and raises ``LockOrderError`` when the new
    edge would close a cycle."""

    def __init__(self) -> None:
        self._meta = threading.RLock()  # the watch's own bookkeeping
        self._tls = threading.local()
        self._seq = 0
        self._names: dict[int, str] = {}
        self._succ: dict[int, set[int]] = {}
        self._edge_stacks: dict[tuple[int, int], str] = {}
        self._orig_lock = None
        self._orig_rlock = None

    # -- registration -------------------------------------------------

    def _register(self) -> int:
        with self._meta:
            self._seq += 1
            node = self._seq
            self._names[node] = f"{_site()}#{node}"
            return node

    def _held(self) -> list[int]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- the detector -------------------------------------------------

    def _path(self, src: int, dst: int) -> list[int] | None:
        """A directed path src -> ... -> dst in the order graph, or
        None (iterative DFS; called under ``_meta``)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._succ.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def check_acquire(self, node: int) -> None:
        """Record ``held -> node`` edges; raise on a cycle.  Runs
        BEFORE the blocking acquire, so the inversion is reported
        instead of reproduced."""
        held = self._held()
        if not held:
            return
        with self._meta:
            for h in held:
                if h == node or node in self._succ.get(h, ()):
                    continue  # reentrant/known edge: nothing new
                back = self._path(node, h)
                if back is not None:
                    cycle = [self._names[n] for n in back]
                    edges = []
                    for a, b in zip(back, back[1:]):
                        edges.append(
                            f"--- edge {self._names[a]} -> "
                            f"{self._names[b]} first observed at:\n"
                            f"{self._edge_stacks.get((a, b), '?')}")
                    raise LockOrderError(
                        f"lock-order inversion: acquiring "
                        f"{self._names[node]} while holding "
                        f"{self._names[h]}, but the recorded order is "
                        f"{' -> '.join(cycle)} (acquiring here would "
                        "close the cycle; under the right interleave "
                        "this deadlocks)",
                        cycle=tuple(cycle + [self._names[node]]),
                        stacks=tuple(edges + [
                            f"--- closing acquire at:\n{_here()}"]))
                self._succ.setdefault(h, set()).add(node)
                self._edge_stacks[(h, node)] = _here()

    # -- held-stack bookkeeping (wrappers call these) -------------------

    def push(self, node: int) -> None:
        self._held().append(node)

    def pop(self, node: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == node:
                del held[i]
                return


class WatchedLock:
    """A ``threading.Lock`` recording acquisition order (see module
    docstring).  Non-blocking and timeout acquires skip the order
    check — they cannot deadlock — but still maintain the held
    stack."""

    def __init__(self, watch: LockWatch, inner):
        self._watch = watch
        self._inner = inner
        self._node = watch._register()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and timeout == -1:
            self._watch.check_acquire(self._node)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._watch.push(self._node)
        return got

    def release(self) -> None:
        self._inner.release()
        self._watch.pop(self._node)

    def locked(self) -> bool:
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return (f"<WatchedLock {self._watch._names[self._node]} "
                f"wrapping {self._inner!r}>")


class WatchedRLock:
    """A ``threading.RLock`` with order recording and the
    ``Condition`` wait protocol (``_is_owned`` / ``_release_save`` /
    ``_acquire_restore``).  Reentrant re-acquires are depth-counted by
    the owning thread and never re-recorded."""

    def __init__(self, watch: LockWatch, inner):
        self._watch = watch
        self._inner = inner
        self._node = watch._register()
        self._owner: int | None = None
        self._count = 0

    def _mine(self) -> bool:
        return self._owner == threading.get_ident()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._mine():
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._count += 1
            return got
        if blocking and timeout == -1:
            self._watch.check_acquire(self._node)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._count = 1
            self._watch.push(self._node)
        return got

    def release(self) -> None:
        mine = self._mine()
        self._inner.release()
        if mine:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                self._watch.pop(self._node)

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol -------------------------------------------

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        saved = (self._owner, self._count)
        self._owner, self._count = None, 0
        self._watch.pop(self._node)
        return (state, saved)

    def _acquire_restore(self, state) -> None:
        inner_state, (owner, count) = state
        self._watch.check_acquire(self._node)
        self._inner._acquire_restore(inner_state)
        self._owner, self._count = owner, count
        self._watch.push(self._node)

    def __repr__(self) -> str:
        return (f"<WatchedRLock {self._watch._names[self._node]} "
                f"wrapping {self._inner!r}>")


_armed: LockWatch | None = None


def arm() -> LockWatch:
    """Patch ``threading.Lock``/``threading.RLock`` so every lock
    created from now on is watched; returns the watch.  One armed
    session at a time (nesting would tangle the restore order)."""
    global _armed
    if _armed is not None:
        raise ValueError(
            "lockwatch is already armed; disarm the previous watch "
            "first (one session at a time)")
    watch = LockWatch()
    watch._orig_lock = threading.Lock
    watch._orig_rlock = threading.RLock

    def make_lock():
        return WatchedLock(watch, watch._orig_lock())

    def make_rlock():
        return WatchedRLock(watch, watch._orig_rlock())

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _armed = watch
    return watch


def disarm(watch: LockWatch) -> None:
    """Restore the real lock factories.  Watched locks already handed
    out keep working (they wrap real locks); only the graph stops
    growing new nodes."""
    global _armed
    if watch._orig_lock is not None:
        threading.Lock = watch._orig_lock
        threading.RLock = watch._orig_rlock
    if _armed is watch:
        _armed = None
