"""Deterministic test instrumentation (fault injection + the
lock-order watchdog).  Not part of the serving API surface; production
code paths only touch ``faults.fire``, which is a dict lookup
returning immediately when nothing is armed — ``lockwatch`` patches
the lock factories only inside an explicitly armed window (chaos/soak
CI legs, the ``lockwatch`` pytest marker)."""

from dcf_tpu.testing import faults, lockwatch  # noqa: F401
