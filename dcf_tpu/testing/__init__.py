"""Deterministic test instrumentation (fault injection).  Not part of the
serving API surface; production code paths only touch ``faults.fire``,
which is a dict lookup returning immediately when nothing is armed."""

from dcf_tpu.testing import faults  # noqa: F401
