"""Bitsliced AES-256 over packed bit-planes — the TPU hot-path cipher.

State layout: planes on axis 0 (128 planes per block, p = byte*8 + bit,
LSB-first), arbitrary trailing dims of packed uint32 lanes (32 batch
elements per word).  Every operation is XOR/AND on whole planes:

* SubBytes  — the derived tower-field circuit (ops.sbox_circuit), applied to
  all 16 byte positions at once by reshaping to [16, 8, ...].
* ShiftRows — a static permutation of byte-plane groups (free at trace time).
* MixColumns — xtime is a plane reindex + conditional XOR (0x1B feedback into
  bits 0, 1, 3, 4), columns vectorized.
* AddRoundKey — one XOR with per-plane masks (0 / 0xFFFFFFFF) precomputed on
  the host from the expanded key schedule.

No gathers, no byte arithmetic, no data-dependent anything: this is why it
runs on the VPU at full width while the table-AES path crawled.
Generic over numpy/jnp (``xp``): the numpy path is the test oracle, the jnp
path is what the eval scan traces.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.ops.aes import expand_key_np
from dcf_tpu.ops.sbox_circuit import sbox_planes_bp113 as sbox_planes
from dcf_tpu.spec import SHIFT_ROWS
from dcf_tpu.utils.bits import bitmajor_perm, byte_bits_lsb, expand_bits_to_masks

__all__ = [
    "round_key_masks",
    "round_key_masks_bitmajor",
    "aes256_encrypt_planes",
    "aes256_encrypt_planes_bitmajor",
    "aes256_encrypt_planes_bitmajor_v2",
    "aes256_encrypt_planes_bitmajor_v3",
    "aes256_encrypt_blocks_bitmajor",
    "aes256_encrypt_blocks_bitmajor_v3",
    "prep_rk_bitmajor_v3",
    "aes_walk_cipher_v3",
]


def round_key_masks(key: bytes) -> np.ndarray:
    """32-byte key -> uint32 [15, 128] plane masks (0 / 0xFFFFFFFF)."""
    rk = expand_key_np(key)  # [15, 16] uint8
    return expand_bits_to_masks(byte_bits_lsb(rk))  # [15, 128]


def _xtime_planes(xp, a):
    """GF(2^8) doubling at the bit-plane level.

    a: [..., 8, *lanes] with the bit axis at position ``-1 - lane_dims``?  To
    keep indexing simple this helper takes the bit axis FIRST: a[bit] is a
    plane stack [8, ...].  Returns the same shape.
    """
    return xp.stack(
        [
            a[7],
            a[0] ^ a[7],
            a[1],
            a[2] ^ a[7],
            a[3] ^ a[7],
            a[4],
            a[5],
            a[6],
        ]
    )


def aes256_encrypt_planes(xp, rk_masks, planes, ones):
    """Encrypt blocks in plane representation.

    xp: numpy or jax.numpy.  rk_masks: uint32 [15, 128] (host-precomputed).
    planes: uint32 [128, *rest] packed planes.  ones: all-ones uint32 scalar
    or broadcastable array.  Returns uint32 [128, *rest].
    """
    rest = planes.shape[1:]
    ark_shape = (128,) + (1,) * len(rest)

    def ark(s, rnd):
        return s ^ rk_masks[rnd].reshape(ark_shape)

    def sub_shift(s):
        # SubBytes on all 16 byte positions, then ShiftRows (byte-plane
        # permutation folded into the same reshape round-trip).
        b = s.reshape(16, 8, *rest)
        out_bits = sbox_planes([b[:, i] for i in range(8)], ones)
        sb = xp.stack(out_bits, axis=1)  # [16, 8, *rest]
        return sb[np.array(SHIFT_ROWS)]

    def mix(sb):
        # sb: [16, 8, *rest] -> columns [4, 4, 8, *rest]; bit axis first for
        # xtime: a_i = [8, 4(col), *rest].
        cols = sb.reshape(4, 4, 8, *rest)
        a = [xp.moveaxis(cols[:, i], 1, 0) for i in range(4)]
        xt = [_xtime_planes(xp, ai) for ai in a]
        out0 = xt[0] ^ xt[1] ^ a[1] ^ a[2] ^ a[3]
        out1 = a[0] ^ xt[1] ^ xt[2] ^ a[2] ^ a[3]
        out2 = a[0] ^ a[1] ^ xt[2] ^ xt[3] ^ a[3]
        out3 = xt[0] ^ a[0] ^ a[1] ^ a[2] ^ xt[3]
        # [4(byte), 8(bit), 4(col), *rest] -> [4(col), 4(byte), 8, *rest]
        stacked = xp.stack([out0, out1, out2, out3])
        return xp.moveaxis(stacked, 2, 0).reshape(128, *rest)

    s = ark(planes, 0)
    for rnd in range(1, 14):
        s = ark(mix(sub_shift(s)), rnd)
    return ark(sub_shift(s).reshape(128, *rest), 14)


# ---------------------------------------------------------------------------
# Bit-major variant (the Pallas kernel layout).
#
# Plane order within one 128-plane block: p' = bit*16 + byte (utils.bits.
# bitmajor_perm), so the 8 S-box input planes are CONTIGUOUS 16-row sublane
# slices of the state — no strided sublane gathers inside the kernel, which
# is what Mosaic lowers well.  Semantics identical to the byte-major path.
# ---------------------------------------------------------------------------


def round_key_masks_bitmajor(key: bytes):
    """32-byte key -> int32 [15, 128, 1] bit-major plane masks (0 / -1)."""
    masks = round_key_masks(key)[:, bitmajor_perm(16)]  # [15, 128] uint32
    return masks.view(np.int32)[:, :, None].copy()


def aes256_encrypt_planes_bitmajor(xp, rk_all, state, ones):
    """Encrypt blocks in bit-major plane representation.

    rk_all: [15, 128, 1] plane masks (round_key_masks_bitmajor).  state:
    [128, L] packed planes, bit-major order.  ones: all-ones scalar of the
    state dtype.  Returns [128, L].  Works for numpy and jnp (including
    inside a Pallas kernel, where every op below is sublane-contiguous).
    """
    l = state.shape[-1]

    def sub(s):
        s3 = s.reshape(8, 16, l)
        return xp.stack(sbox_planes([s3[i] for i in range(8)], ones))

    def shift(sb):
        # [8, 16, L] -> [8, 4c, 4r, L]; dest (c, r) <- src ((c+r)%4, r),
        # i.e. row r of the AES state rotates left by r columns.
        a = sb.reshape(8, 4, 4, l)
        rows = [a[:, :, 0, :]]
        for r in range(1, 4):
            x = a[:, :, r, :]
            rows.append(xp.concatenate([x[:, r:], x[:, :r]], axis=1))
        return xp.stack(rows, axis=2)

    def xt(a):
        # GF(2^8) doubling on the bit axis (axis 0) of [8, 4c, 4r, L].
        return xp.stack(
            [a[7], a[0] ^ a[7], a[1], a[2] ^ a[7], a[3] ^ a[7], a[4], a[5], a[6]]
        )

    def mix(a):
        r1 = xp.concatenate([a[:, :, 1:], a[:, :, :1]], axis=2)
        r2 = xp.concatenate([a[:, :, 2:], a[:, :, :2]], axis=2)
        r3 = xp.concatenate([a[:, :, 3:], a[:, :, :3]], axis=2)
        return xt(a) ^ xt(r1) ^ r1 ^ r2 ^ r3

    s = state ^ rk_all[0]
    for rnd in range(1, 14):
        s = mix(shift(sub(s))).reshape(128, l) ^ rk_all[rnd]
    return shift(sub(s)).reshape(128, l) ^ rk_all[14]


# ---------------------------------------------------------------------------
# Block-permutation variant of the bit-major cipher (the fast kernel path).
#
# ShiftRows∘MixColumns is re-expressed per bit-block as a 4-term XOR of
# statically byte-permuted [16, L] blocks.  With state byte index 4c + r
# (column-major AES state) and the MDS circulant {02,03,01,01} indexed by
# row distance d = r' - r:
#
#     out(c, r) = Σ_d m_d ⊗ sb((c + r + d) % 4, (r + d) % 4)
#
# so each distance d contributes ONE fixed byte permutation P_d applied to a
# whole bit-block (m_0 = xtime, m_1 = xtime ⊕ 1, m_2 = m_3 = 1):
#
#     out[b] = P0(xt[b]) ^ P1(xt[b] ^ sb[b]) ^ P2(sb[b]) ^ P3(sb[b])
#
# Everything stays in [16, L] tiles (full 8-sublane vregs) — no [4, ...]
# intermediates, no cross-bit stacks — which is why this lowers ~4x faster
# under Mosaic than the reshape/concat formulation above.  Semantics are
# identical (tested against the v1 path and the numpy oracle).
# ---------------------------------------------------------------------------


def _mcsr_perms() -> tuple[np.ndarray, np.ndarray]:
    perms = np.empty((4, 16), dtype=np.int32)
    for d in range(4):
        for c in range(4):
            for r in range(4):
                perms[d, 4 * c + r] = 4 * ((c + r + d) % 4) + (r + d) % 4
    sr = np.array(
        [4 * ((c + r) % 4) + r for c in range(4) for r in range(4)],
        dtype=np.int32,
    )
    return perms, sr


_MCSR_PERMS, _SR_PERM = _mcsr_perms()


def _xt_blocks(b):
    """GF(2^8) xtime at block level: b is a list of 8 bit-blocks [16, L]."""
    return [b[7], b[0] ^ b[7], b[1], b[2] ^ b[7], b[3] ^ b[7],
            b[4], b[5], b[6]]


def _perm_rows(xp, x, perm):
    """Static row permutation of x [16, L] (Pallas-safe: no index arrays).

    Emitted as a concat of maximal contiguous source slices so Mosaic sees
    plain static slicing instead of a gather with captured constants.
    """
    if xp is np:
        return x[perm]
    parts = []
    i = 0
    while i < len(perm):
        j = i
        while j + 1 < len(perm) and perm[j + 1] == perm[j] + 1:
            j += 1
        parts.append(x[perm[i]:perm[j] + 1])
        i = j + 1
    return xp.concatenate(parts, axis=0)


def aes256_encrypt_blocks_bitmajor(xp, rk_all, blocks, ones):
    """Encrypt in bit-major block-list representation.

    rk_all: [15, 128, 1] plane masks (round_key_masks_bitmajor).  blocks:
    list of 8 arrays [16, L] (block i = bit-i planes of all 16 bytes).
    Returns a list of 8 [16, L] blocks.  xp is numpy or jnp.
    """
    rk = rk_all.reshape(15, 8, 16, 1)
    p0, p1, p2, p3 = (list(_MCSR_PERMS[d]) for d in range(4))
    b = [blocks[i] ^ rk[0, i] for i in range(8)]
    for rnd in range(1, 14):
        sb = sbox_planes([b[i] for i in range(8)], ones)
        xb = _xt_blocks(sb)
        b = [
            _perm_rows(xp, xb[i], p0)
            ^ _perm_rows(xp, xb[i] ^ sb[i], p1)
            ^ _perm_rows(xp, sb[i], p2)
            ^ _perm_rows(xp, sb[i], p3)
            ^ rk[rnd, i]
            for i in range(8)
        ]
    sb = sbox_planes([b[i] for i in range(8)], ones)
    return [_perm_rows(xp, sb[i], list(_SR_PERM)) ^ rk[14, i]
            for i in range(8)]


def aes256_encrypt_planes_bitmajor_v2(xp, rk_all, state, ones):
    """Drop-in for ``aes256_encrypt_planes_bitmajor`` via the block path."""
    l = state.shape[-1]
    s3 = state.reshape(8, 16, l)
    out = aes256_encrypt_blocks_bitmajor(
        xp, rk_all, [s3[i] for i in range(8)], ones)
    return xp.stack(out).reshape(128, l)


# ---------------------------------------------------------------------------
# Conjugated-ShiftRows variant (v3): the round permutations of v2 are
# generic 16-row gathers (16 slice parts each under Mosaic).  Conjugating
# the round state by powers of ShiftRows turns them into near-rolls:
#
#   keep state_k in P_SR^{-k} byte order; then the d-term permutation
#   P_SR^{k}∘P_d∘P_SR^{-(k+1)} maps (c, r) <- (c + (k+1)d, r + d) — a 2D
#   cyclic roll with at most 8 contiguous runs, and the d=0 term is the
#   IDENTITY.  Per round: 3 cheap rolls instead of 4 generic gathers
#   (24 slice parts vs 64); one generic realign restores true byte order in
#   the final (mix-less) round.  Round keys are pre-permuted into each
#   round's conjugated order once per call (hoist `prep_rk_bitmajor_v3`
#   outside any inner loop).  Bit-identical to v1/v2 (tests).
# ---------------------------------------------------------------------------


def _conjugated_perms():
    sr = np.asarray(_SR_PERM)
    sr_inv = np.argsort(sr)
    qs = [np.arange(16)]  # q_k = index array of P_SR^{-k}
    for _ in range(14):
        qs.append(sr_inv[qs[-1]])
    term_perms = []  # per round 1..13: [e_1, e_2, e_3] (e_0 is identity)
    rk_orders = []   # per round 1..13: row order of that round's key mask
    for rnd in range(1, 14):
        q, qp = qs[rnd - 1], qs[rnd]
        qinv = np.argsort(q)
        es = [qinv[_MCSR_PERMS[d][qp]] for d in range(4)]
        assert np.array_equal(es[0], np.arange(16))
        term_perms.append([list(e) for e in es[1:]])
        rk_orders.append(list(qp))
    final_perm = list(np.argsort(qs[13])[sr])  # realign to true byte order
    return term_perms, rk_orders, final_perm


_V3_TERM_PERMS, _V3_RK_ORDERS, _V3_FINAL_PERM = _conjugated_perms()


def prep_rk_bitmajor_v3(xp, rk_all):
    """[15, 128, L] round-key masks -> v3 conjugated-order masks.

    L is usually 1 (one cipher broadcast over all lanes); the narrow-walk
    kernel passes lane-wide masks (L = lanes) for its two-cipher batch.
    One-time cost; hoist outside the per-level loop in kernels."""
    rk = rk_all.reshape(15, 8, 16, rk_all.shape[-1])
    out = [rk[0]]
    for rnd in range(1, 14):
        order = _V3_RK_ORDERS[rnd - 1]
        out.append(xp.stack([_perm_rows(xp, rk[rnd, i], order)
                             for i in range(8)]))
    out.append(rk[14])
    return xp.stack(out)


def _rk_block(rk, rnd, i, n_rest: int):
    """Round-key block [16, L] viewed for states with n_rest trailing dims
    (L = 1 broadcasts one cipher everywhere; L = lanes is per-lane keys)."""
    blk = rk[rnd, i]
    return blk.reshape((16,) + (1,) * (n_rest - 1) + (blk.shape[-1],))


def aes256_encrypt_blocks_bitmajor_v3(xp, rk_prepped, blocks, ones):
    """v3 cipher over bit-block lists; rk_prepped from prep_rk_bitmajor_v3.

    blocks: list of 8 arrays [16, *rest] in TRUE byte order; returns the
    same (the conjugated order is internal only).  Trailing dims are
    arbitrary: [16, L] for the points-in-lanes kernel, [16, M, Kw] for the
    keys-in-lanes kernel.
    """
    rk = rk_prepped
    nr = blocks[0].ndim - 1
    b = [blocks[i] ^ _rk_block(rk, 0, i, nr) for i in range(8)]
    for rnd in range(1, 14):
        e1, e2, e3 = _V3_TERM_PERMS[rnd - 1]
        sb = sbox_planes([b[i] for i in range(8)], ones)
        xb = _xt_blocks(sb)
        b = [
            xb[i]
            ^ _perm_rows(xp, xb[i] ^ sb[i], e1)
            ^ _perm_rows(xp, sb[i], e2)
            ^ _perm_rows(xp, sb[i], e3)
            ^ _rk_block(rk, rnd, i, nr)
            for i in range(8)
        ]
    sb = sbox_planes([b[i] for i in range(8)], ones)
    return [_perm_rows(xp, sb[i], _V3_FINAL_PERM) ^ _rk_block(rk, 14, i, nr)
            for i in range(8)]


def aes256_encrypt_planes_bitmajor_v3(xp, rk_all, state, ones):
    """Drop-in for ``aes256_encrypt_planes_bitmajor`` via the v3 path."""
    return aes_walk_cipher_v3(xp, prep_rk_bitmajor_v3(xp, rk_all),
                              state, ones)


def aes_walk_cipher_v3(xp, rk_prepped, state, ones):
    """The exact cipher body the walk kernels run: prepped round keys in,
    [128, *rest] planes in/out.  Kept as a standalone function so the CPU
    test suite can exercise the kernel's cipher glue (reshape/blocks/stack)
    without Mosaic (tests/test_bitsliced.py)."""
    rest = state.shape[1:]
    s3 = state.reshape(8, 16, *rest)
    out = aes256_encrypt_blocks_bitmajor_v3(
        xp, rk_prepped, [s3[i] for i in range(8)], ones)
    return xp.stack(out).reshape(128, *rest)
