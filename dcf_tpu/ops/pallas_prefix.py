"""Prefix-shared DCF batch evaluation kernel.

A batch of M random points shares the top k ~ log2(M) levels of the GGM
walk.  The from-root walk kernel (ops.pallas_eval) pays M PRG calls per
level for all n levels; here the top k levels are expanded ONCE as a tree
(ops.pallas_tree.tree_expand_raw, ~2^{k+1} PRG calls — key-only material,
cached per key like the CW image), each point GATHERS its (s, v, t) carry
from the 2^k-node frontier, and this kernel walks only the remaining
n - k levels.  Work per batch: M*(n-k) + 2^{k+1} PRG calls instead of
M*n.  (Reference workload: the reference walks every level per point,
/root/reference/src/lib.rs:163-204, benches/dcf_batch_eval.rs:17-39.)

Measured cost structure on v5e (benchmarks/micro_gather.py): the XLA row
gather costs ~3.4-3.7 ms per 2^20 points at k <= 21 ([2^k, 8]-int32
rows; 4x cliff at 2^22 TOTAL stacked rows — the 128 MB table — and 2x
for non-power-of-2 row widths), and
repacking gathered byte rows into the kernel's bit-major plane layout in
XLA costs ~4.4 ms per table — so the repack runs INSIDE this kernel
instead as 32x32 bit transposes (5 butterfly steps of static sublane
slice/concats, Hacker's Delight 7-3): ~0.5 ms per table at M = 2^20,
fused into the walk dispatch.

The t-bit rides in the s rows: every frontier seed has bit-major plane 15
(byte 15, bit 0) cleared by the Hirose 8*lam-1 output mask (reference
src/prg.rs:65-68) — the one bit of s that is structurally ZERO after
level >= 1 — so the gather stays at the fast power-of-2 row width with no
separate t gather.  The kernel extracts plane 15 as the packed t lane
words and re-clears it.

Input row layout per tile (prepared by one XLA transpose of the gathered
rows): [4, 32, wt] int32 where element (i, j, w) = int32 column i of the
row gathered for point 32*(tile base + w) + (31 - j) — the j-reversal and
the output-row reversal of the butterfly network are both absorbed into
static index maps, costing nothing at runtime.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops._compat import CompilerParams as _CompilerParams

from dcf_tpu.ops.group_accum import (group_width, planes_add_bitmajor16,
                                     planes_neg_bitmajor16)
from dcf_tpu.ops.pallas_eval import DEFAULT_TILE_WORDS, make_aes, walk_levels

__all__ = ["dcf_eval_prefix_pallas", "rows_to_state_planes"]

_MASKS = (0x0000FFFF, 0x00FF00FF, 0x0F0F0F0F, 0x33333333, 0x55555555)


def _transpose32_raw(xp, x):
    """[32, L] int32 butterfly bit transpose: out row r bit j =
    in row 31-j bit 31-r (per lane).  Both reversals are the caller's to
    absorb (static layouts)."""
    k = 16
    for m_val in _MASKS:
        m = jnp.int32(m_val)
        parts = []
        for base in range(0, 32, 2 * k):
            a = x[base:base + k]
            b = x[base + k:base + 2 * k]
            b_shr = jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(b, jnp.uint32) >> k, jnp.int32)
            t = (a ^ b_shr) & m
            parts.append(a ^ t)
            parts.append(b ^ (t << k))
        x = xp.concatenate(parts, axis=0)
        k //= 2
    return x


def rows_to_state_planes(xp, rows):
    """[4, 32, wt] j-reversed row block -> [128, wt] bit-major planes.

    Plane order p' = bit*16 + byte (ops.pallas_eval layout); lane word w
    bit j = point 32*w + j.
    """
    planes = [None] * 128
    for i in range(4):
        tr = _transpose32_raw(xp, rows[i])
        for r in range(32):
            b = 31 - r  # true bit index within int32 column i
            byte, bit = i * 4 + b // 8, b % 8
            planes[bit * 16 + byte] = tr[r:r + 1]
    return xp.concatenate(planes, axis=0)


def _kernel(rk_ref, srows_ref, vrows_ref, cw_s_ref, cw_v_ref, cw_np1_ref,
            cw_t_ref, xm_ref, y_ref, *, n_rem: int, interpret: bool,
            group: str = "xor", negate: bool = False):
    wt = xm_ref.shape[3]
    ones = jnp.int32(-1)
    gw = group_width(group)
    aes = make_aes(rk_ref[:], interpret)

    plane_idx = jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0)
    lbm = jnp.where(plane_idx == 15, jnp.int32(0), ones)

    s_planes = rows_to_state_planes(jnp, srows_ref[0])
    v0 = rows_to_state_planes(jnp, vrows_ref[0])
    # t rides in plane 15 of the s rows (structurally zero in a real
    # frontier seed — the Hirose 8*lam-1 mask); extract and re-clear.
    t0 = s_planes[15:16]
    s0 = s_planes & lbm

    s, t, v = walk_levels(aes, lbm, s0, t0, v0, cw_s_ref, cw_v_ref,
                          cw_t_ref, xm_ref, n_rem, group)
    if not gw:
        y_ref[0] = v ^ s ^ (cw_np1_ref[0] & t)
        return
    y = planes_add_bitmajor16(
        v, planes_add_bitmajor16(s, cw_np1_ref[0] & t, gw), gw)
    # Signed-share contract: the party sign is applied at the walk exit
    # (the frontier itself accumulates unsigned).
    y_ref[0] = planes_neg_bitmajor16(y, gw) if negate else y


def dcf_eval_prefix_pallas(
    rk,        # int32 [15, 128, 1]     bit-major round-key masks
    srows,     # int32 [K, 4, 32, W]    gathered s rows (t in plane 15),
               #                        j-reversed tile layout (see module
               #                        docstring)
    vrows,     # int32 [K, 4, 32, W]    gathered v rows
    cw_s_t,    # int32 [K, n_rem, 128, 1]  CW planes for levels k..n-1
    cw_v_t,    # int32 [K, n_rem, 128, 1]
    cw_np1_t,  # int32 [K, 128, 1]
    cw_t,      # int32 [K, n_rem, 2]
    x_mask,    # int32 [Kx, n_rem, 1, W]   lane masks for levels k..n-1
    *,
    tile_words: int = DEFAULT_TILE_WORDS,
    interpret: bool = False,
    group: str = "xor",
    negate: bool = False,
):
    """Walk the remaining n-k levels from gathered frontier carries.

    Party is implicit: the frontier rows were expanded from the party's
    key share (its s0 and t=b entered at level 0 of the tree).  For an
    additive ``group`` the caller passes ``negate=True`` for party 1 (the
    signed-share contract; the walk itself is party-symmetric).  Returns
    y planes int32 [K, 128, W], same layout as ``dcf_eval_pallas``.
    """
    k_num = srows.shape[0]
    n_rem = cw_s_t.shape[1]
    kx, _, _, w = x_mask.shape
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"point words {w} not a multiple of tile {wt}")
    shared = kx == 1

    grid = (k_num, w // wt)
    rows_spec = pl.BlockSpec((1, 4, 32, wt), lambda k, j: (k, 0, 0, j))
    # Same scoped-vmem headroom as the from-root walk kernel: a multi-key
    # grid's block buffering exceeds the 16 MB default (measured 28 MB at
    # K=8, n_rem=110, wt=128).
    return pl.pallas_call(
        partial(_kernel, n_rem=n_rem, interpret=interpret, group=group,
                negate=negate),
        out_shape=jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
        grid=grid,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        in_specs=[
            pl.BlockSpec((15, 128, 1), lambda k, j: (0, 0, 0)),
            rows_spec,
            rows_spec,
            pl.BlockSpec((1, n_rem, 128, 1), lambda k, j: (k, 0, 0, 0)),
            pl.BlockSpec((1, n_rem, 128, 1), lambda k, j: (k, 0, 0, 0)),
            pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0)),
            pl.BlockSpec((1, n_rem, 2), lambda k, j: (k, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n_rem, 1, wt),
                         (lambda k, j: (0, 0, 0, j)) if shared
                         else (lambda k, j: (k, 0, 0, j))),
        ],
        out_specs=pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j)),
        interpret=interpret,
    )(rk, srows, vrows, cw_s_t, cw_v_t, cw_np1_t, cw_t, x_mask)
