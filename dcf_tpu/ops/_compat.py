"""jax version-skew shims for the Pallas kernels.

``pallas.tpu`` renamed ``TPUCompilerParams`` -> ``CompilerParams``;
resolve whichever this jax ships so the kernels survive version skew
instead of dying on AttributeError (the sharded-layer counterpart lives
in ``dcf_tpu.parallel._compat``).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

__all__ = ["CompilerParams"]

CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
