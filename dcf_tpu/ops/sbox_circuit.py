"""Boolean straight-line circuit for the AES S-box, derived at import time.

The TPU hot path evaluates AES bitsliced: the state lives as bit-planes
packed 32-per-uint32 across the batch, and SubBytes must therefore be a
branch-free XOR/AND/NOT circuit over planes — no table lookups (gathers are
what made the table-AES path 30x slower than one CPU core).

Rather than transcribing a published gate list (error-prone, unverifiable by
eye), this module *derives* a circuit from the tower-field structure
GF(((2^2)^2)^2) — the classical Canright decomposition — and verifies it
exhaustively against the generated AES_SBOX for all 256 inputs at import.
The derivation:

  1. Build GF(4) = GF(2)[w]/(w^2+w+1), GF(16) = GF(4)[z]/(z^2+z+N),
     GF(256) = GF(16)[y]/(y^2+y+M), picking N, M that make the quadratics
     irreducible (searched, not assumed).
  2. Find a GF(2)-linear isomorphism A from the AES field
     GF(2)[x]/(x^8+x^4+x^3+x+1) to the tower (map x to a root of the AES
     polynomial in the tower; verified multiplicative).
  3. Inversion in the tower: for g = a*y + b (a, b in GF(16)),
     g^-1 = (a*d)*y + (a+b)*d with d = (a^2*M + a*b + b^2)^-1 — one GF(16)
     inversion plus three GF(16) multiplications; a^2*M and b^2 are
     GF(2)-linear maps; the GF(16) inversion is a tiny 4-bit ANF.
  4. S-box(x) = Aff(inv(x)): fold Aff . A^-1 into one output matrix.

The exported evaluator works on *packed* planes (uint32 words, 32 batch
elements per word): XOR/AND are bitwise, NOT is ^ones.  It is generic over
numpy/jnp via the ``xp`` namespace argument, so the same circuit is the CPU
reference and the TPU kernel body.

Gate budget: the exported ``SBOX_NONLINEAR_GATES`` (computed from the derived
structure: 48 bilinear ANDs across the three GF(16) multiplies + the GF(16)
inversion's degree->1 ANF monomial products) plus linear XOR layers and two
8x8 GF(2) edge matrices; all data-independent — constant-time by construction.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.spec import AES_SBOX

__all__ = [
    "sbox_planes",
    "sbox_planes_bp113",
    "IN_MATRIX",
    "OUT_MATRIX",
    "OUT_CONST",
    "SBOX_NONLINEAR_GATES",
]

# ---------------------------------------------------------------------------
# Field tables (plain ints; derivation only, never on the hot path).
# ---------------------------------------------------------------------------


def _gf256_mul(a: int, b: int) -> int:
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11B
        b >>= 1
    return r


def _gf4_mul(a: int, b: int) -> int:
    # GF(4) as bits (hi, lo) with w^2 = w + 1.
    a1, a0 = a >> 1, a & 1
    b1, b0 = b >> 1, b & 1
    hh = a1 & b1
    lo = (a0 & b0) ^ hh
    hi = (a1 & b0) ^ (a0 & b1) ^ hh
    return (hi << 1) | lo


def _gf16_mul_tower(a: int, b: int, n_const: int) -> int:
    # GF(16) as pairs (hi, lo) of GF(4) with z^2 = z + N.
    a1, a0 = a >> 2, a & 3
    b1, b0 = b >> 2, b & 3
    hh = _gf4_mul(a1, b1)
    lo = _gf4_mul(a0, b0) ^ _gf4_mul(hh, n_const)
    hi = _gf4_mul(a1, b0) ^ _gf4_mul(a0, b1) ^ hh
    return (hi << 2) | lo


def _gf256_mul_tower(a: int, b: int, n_const: int, m_const: int) -> int:
    # GF(256) as pairs (hi, lo) of GF(16) with y^2 = y + M.
    a1, a0 = a >> 4, a & 15
    b1, b0 = b >> 4, b & 15
    hh = _gf16_mul_tower(a1, b1, n_const)
    lo = _gf16_mul_tower(a0, b0, n_const) ^ _gf16_mul_tower(hh, m_const, n_const)
    hi = (
        _gf16_mul_tower(a1, b0, n_const)
        ^ _gf16_mul_tower(a0, b1, n_const)
        ^ hh
    )
    return (hi << 4) | lo


def _pick_tower_constants() -> tuple[int, int]:
    """Smallest (N, M) making z^2+z+N and y^2+y+M irreducible."""
    n_const = next(
        n
        for n in range(1, 4)
        if all(_gf4_mul(z, z) ^ z ^ n != 0 for z in range(4))
    )
    m_const = next(
        m
        for m in range(1, 16)
        if all(_gf16_mul_tower(y, y, n_const) ^ y ^ m != 0 for y in range(16))
    )
    return n_const, m_const


_N, _M = _pick_tower_constants()


def _find_isomorphism() -> np.ndarray:
    """8x8 GF(2) matrix A: tower_bits = A @ aes_bits (mod 2).

    Found by locating a root theta of the AES polynomial x^8+x^4+x^3+x+1 in
    the tower field and mapping the polynomial basis x^i -> theta^i; basis
    maps of root powers are multiplicative by construction, and _verify()
    checks the composed S-box against AES_SBOX for all 256 inputs.
    """

    def tower_pow(g: int, e: int) -> int:
        r = 1
        for _ in range(e):
            r = _gf256_mul_tower(r, g, _N, _M)
        return r

    for theta in range(2, 256):
        # Evaluate theta^8 + theta^4 + theta^3 + theta + 1 in the tower.
        val = tower_pow(theta, 8) ^ tower_pow(theta, 4) ^ tower_pow(theta, 3) ^ theta ^ 1
        if val == 0:
            a = np.zeros((8, 8), dtype=np.uint8)
            for i in range(8):
                p = tower_pow(theta, i)
                for j in range(8):
                    a[j, i] = (p >> j) & 1
            return a
    # dcflint: disable=typed-error import-time mathematical invariant of
    # the derived tower field, unreachable unless the derivation itself is
    # edited; AssertionError is the semantically right class
    raise AssertionError("no root of the AES polynomial in the tower field")


def _matmul_gf2(mat: np.ndarray, x: int) -> int:
    bits = np.array([(x >> i) & 1 for i in range(8)], dtype=np.uint8)
    out = (mat @ bits) & 1
    return int(sum(int(b) << i for i, b in enumerate(out)))


IN_MATRIX = _find_isomorphism()

# AES affine layer: Aff(q) = L(q) ^ 0x63 with L(q) bit i = q_i ^ q_{i+4} ^
# q_{i+5} ^ q_{i+6} ^ q_{i+7} (indices mod 8).
_AFF = np.zeros((8, 8), dtype=np.uint8)
for _i in range(8):
    for _d in (0, 4, 5, 6, 7):
        _AFF[_i, (_i + _d) % 8] ^= 1

def _gf2_inv(mat: np.ndarray) -> np.ndarray:
    n = mat.shape[0]
    aug = np.concatenate([mat.copy() % 2, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = next(r for r in range(col, n) if aug[r, col])
        aug[[col, piv]] = aug[[piv, col]]
        for r in range(n):
            if r != col and aug[r, col]:
                aug[r] ^= aug[col]
    return aug[:, n:]


_IN_INV = _gf2_inv(IN_MATRIX)
OUT_MATRIX = (_AFF @ _IN_INV) % 2
OUT_CONST = 0x63

# GF(16) linear maps used by the inversion: xi(a) = a^2 * M and sq(b) = b^2
# (both GF(2)-linear in GF(2^k) extensions).
_XI = np.zeros((4, 4), dtype=np.uint8)
_SQ = np.zeros((4, 4), dtype=np.uint8)
for _i in range(4):
    _sq = _gf16_mul_tower(1 << _i, 1 << _i, _N)
    _x = _gf16_mul_tower(_sq, _M, _N)
    for _j in range(4):
        _XI[_j, _i] = (_x >> _j) & 1
        _SQ[_j, _i] = (_sq >> _j) & 1

# GF(16) multiply as a bilinear form: out_k = XOR_{i,j in BILIN[k]} a_i & b_j.
_BILIN: list[list[tuple[int, int]]] = [[] for _ in range(4)]
for _i in range(4):
    for _j in range(4):
        p = _gf16_mul_tower(1 << _i, 1 << _j, _N)
        for _k in range(4):
            if (p >> _k) & 1:
                _BILIN[_k].append((_i, _j))

# GF(16) inversion as ANF over 4 bits (Moebius transform of the truth table).
_INV16 = [0] * 16
for _a in range(1, 16):
    _INV16[_a] = next(
        b for b in range(16) if _gf16_mul_tower(_a, b, _N) == 1
    )
# inv(0) = 0 matches the paper's convention (0 has no inverse; AES maps 0->0).


def _anf(table: list[int], nbits_in: int, nbits_out: int) -> list[list[int]]:
    """Per output bit, the list of monomials (as input-bit masks) in its ANF."""
    out = []
    for k in range(nbits_out):
        coeffs = [(table[x] >> k) & 1 for x in range(1 << nbits_in)]
        # Moebius transform.
        for i in range(nbits_in):
            for x in range(1 << nbits_in):
                if x & (1 << i):
                    coeffs[x] ^= coeffs[x ^ (1 << i)]
        out.append([x for x in range(1 << nbits_in) if coeffs[x]])
    return out


_INV16_ANF = _anf(_INV16, 4, 4)

# Count nonlinear gates for the docstring claim (ANDs: bilinear products are
# shared across the three multiplies' structure; monomial products shared).
SBOX_NONLINEAR_GATES = 3 * 16 + sum(
    1 for bit in _INV16_ANF for m in bit if bin(m).count("1") > 1
)


# ---------------------------------------------------------------------------
# Plane-level evaluators (work on packed uint32 words or any bitwise type).
# ---------------------------------------------------------------------------


def _apply_gf2_matrix(mat: np.ndarray, planes: list, zero):
    out = []
    for k in range(mat.shape[0]):
        acc = None
        for i in range(mat.shape[1]):
            if mat[k, i]:
                acc = planes[i] if acc is None else acc ^ planes[i]
        out.append(zero if acc is None else acc)
    return out


def _gf16_mul_planes(a: list, b: list):
    prod = {}
    for i in range(4):
        for j in range(4):
            prod[(i, j)] = a[i] & b[j]
    out = []
    for k in range(4):
        acc = None
        for ij in _BILIN[k]:
            acc = prod[ij] if acc is None else acc ^ prod[ij]
        out.append(acc)
    return out


def _gf16_inv_planes(x: list, ones):
    # Evaluate the 4-bit ANF; monomial products shared across output bits.
    mono: dict[int, object] = {}

    def monomial(mask: int):
        if mask in mono:
            return mono[mask]
        low = mask & (-mask)
        rest = mask ^ low
        idx = low.bit_length() - 1
        val = x[idx] if rest == 0 else monomial(rest) & x[idx]
        mono[mask] = val
        return val

    out = []
    for bit_monos in _INV16_ANF:
        acc = None
        for m in bit_monos:
            term = ones if m == 0 else monomial(m)
            acc = term if acc is None else acc ^ term
        out.append(acc)
    return out


def sbox_planes(bits: list, ones):
    """AES S-box over 8 bit-planes (LSB-first), packed or boolean.

    ``bits[i]`` is the plane of input bit i; ``ones`` is the all-ones value
    of the same dtype/shape semantics (e.g. uint32(0xFFFFFFFF) broadcastable
    array).  Returns 8 output planes, LSB-first.  Works for numpy and jnp.
    """
    zero = ones ^ ones
    t = _apply_gf2_matrix(IN_MATRIX, bits, zero)
    b_lo, a_hi = t[:4], t[4:]
    # d_pre = a^2*M + a*b + b^2   (a = high nibble, b = low nibble)
    xi_a = _apply_gf2_matrix(_XI, a_hi, zero)
    sq_b = _apply_gf2_matrix(_SQ, b_lo, zero)
    ab = _gf16_mul_planes(a_hi, b_lo)
    d_pre = [xi_a[k] ^ ab[k] ^ sq_b[k] for k in range(4)]
    d = _gf16_inv_planes(d_pre, ones)
    out_hi = _gf16_mul_planes(a_hi, d)
    a_plus_b = [a_hi[k] ^ b_lo[k] for k in range(4)]
    out_lo = _gf16_mul_planes(a_plus_b, d)
    inv_planes = out_lo + out_hi
    res = _apply_gf2_matrix(OUT_MATRIX, inv_planes, zero)
    return [res[i] ^ ones if (OUT_CONST >> i) & 1 else res[i] for i in range(8)]


def sbox_planes_bp113(bits: list, ones):
    """AES S-box as the Boyar-Peralta 113-gate circuit (32 AND, 77 XOR,
    4 XNOR) — "A new combinational logic minimization technique with
    applications to cryptology", J. Boyar & R. Peralta, SEA 2010.

    Same contract as ``sbox_planes`` (8 LSB-first planes in/out) but ~2x
    fewer ops than the derived tower circuit, which is what the VPU hot
    path wants: the kernel cost is dominated by per-gate vector ops.
    Verified exhaustively against AES_SBOX at import, like the derived
    circuit.
    """
    # The published netlist names inputs U0..U7 / outputs S0..S7 MSB-first.
    x0, x1, x2, x3, x4, x5, x6, x7 = bits[::-1]
    # Top linear layer (23 XOR): shared factors of the inversion inputs.
    y14 = x3 ^ x5
    y13 = x0 ^ x6
    y9 = x0 ^ x3
    y8 = x0 ^ x5
    t0 = x1 ^ x2
    y1 = t0 ^ x7
    y4 = y1 ^ x3
    y12 = y13 ^ y14
    y2 = y1 ^ x0
    y5 = y1 ^ x6
    y3 = y5 ^ y8
    t1 = x4 ^ y12
    y15 = t1 ^ x5
    y20 = t1 ^ x1
    y6 = y15 ^ x7
    y10 = y15 ^ t0
    y11 = y20 ^ y9
    y7 = x7 ^ y11
    y17 = y10 ^ y11
    y19 = y10 ^ y8
    y16 = t0 ^ y11
    y21 = y13 ^ y16
    y18 = x0 ^ y16
    # Shared nonlinear middle: GF(2^4) inversion tower, 32 AND total.
    t2 = y12 & y15
    t3 = y3 & y6
    t4 = t3 ^ t2
    t5 = y4 & x7
    t6 = t5 ^ t2
    t7 = y13 & y16
    t8 = y5 & y1
    t9 = t8 ^ t7
    t10 = y2 & y7
    t11 = t10 ^ t7
    t12 = y9 & y11
    t13 = y14 & y17
    t14 = t13 ^ t12
    t15 = y8 & y10
    t16 = t15 ^ t12
    t17 = t4 ^ t14
    t18 = t6 ^ t16
    t19 = t9 ^ t14
    t20 = t11 ^ t16
    t21 = t17 ^ y20
    t22 = t18 ^ y19
    t23 = t19 ^ y21
    t24 = t20 ^ y18
    t25 = t21 ^ t22
    t26 = t21 & t23
    t27 = t24 ^ t26
    t28 = t25 & t27
    t29 = t28 ^ t22
    t30 = t23 ^ t24
    t31 = t22 ^ t26
    t32 = t31 & t30
    t33 = t32 ^ t24
    t34 = t23 ^ t33
    t35 = t27 ^ t33
    t36 = t24 & t35
    t37 = t36 ^ t34
    t38 = t27 ^ t36
    t39 = t29 & t38
    t40 = t25 ^ t39
    t41 = t40 ^ t37
    t42 = t29 ^ t33
    t43 = t29 ^ t40
    t44 = t33 ^ t37
    t45 = t42 ^ t41
    z0 = t44 & y15
    z1 = t37 & y6
    z2 = t33 & x7
    z3 = t43 & y16
    z4 = t40 & y1
    z5 = t29 & y7
    z6 = t42 & y11
    z7 = t45 & y17
    z8 = t41 & y10
    z9 = t44 & y12
    z10 = t37 & y3
    z11 = t33 & y4
    z12 = t43 & y13
    z13 = t40 & y5
    z14 = t29 & y2
    z15 = t42 & y9
    z16 = t45 & y14
    z17 = t41 & y8
    # Bottom linear layer (26 XOR + 4 XNOR): affine output transform.
    t46 = z15 ^ z16
    t47 = z10 ^ z11
    t48 = z5 ^ z13
    t49 = z9 ^ z10
    t50 = z2 ^ z12
    t51 = z2 ^ z5
    t52 = z7 ^ z8
    t53 = z0 ^ z3
    t54 = z6 ^ z7
    t55 = z16 ^ z17
    t56 = z12 ^ t48
    t57 = t50 ^ t53
    t58 = z4 ^ t46
    t59 = z3 ^ t54
    t60 = t46 ^ t57
    t61 = z14 ^ t57
    t62 = t52 ^ t58
    t63 = t49 ^ t58
    t64 = z4 ^ t59
    t65 = t61 ^ t62
    t66 = z1 ^ t63
    s0 = t59 ^ t63
    s6 = (t56 ^ t62) ^ ones
    s7 = (t48 ^ t60) ^ ones
    t67 = t64 ^ t65
    s3 = t53 ^ t66
    s4 = t51 ^ t66
    s5 = t47 ^ t65
    s1 = (t64 ^ s3) ^ ones
    s2 = (t55 ^ t67) ^ ones
    return [s7, s6, s5, s4, s3, s2, s1, s0]


# ---------------------------------------------------------------------------
# Exhaustive verification at import (256 inputs, boolean planes).
# ---------------------------------------------------------------------------


def _verify() -> None:
    xs = np.arange(256, dtype=np.uint16)
    bits = [((xs >> i) & 1).astype(bool) for i in range(8)]
    ones = np.ones(256, dtype=bool)
    want = np.frombuffer(AES_SBOX, dtype=np.uint8).astype(np.uint16)
    for fn in (sbox_planes, sbox_planes_bp113):
        out = fn(bits, ones)
        got = np.zeros(256, dtype=np.uint16)
        for i in range(8):
            got |= out[i].astype(np.uint16) << i
        if not np.array_equal(got, want):
            bad = int(np.nonzero(got != want)[0][0])
            # dcflint: disable=typed-error import-time self-check of the
            # derived S-box circuit over all 256 inputs; AssertionError is
            # the semantically right class for a broken derivation
            raise AssertionError(
                f"{fn.__name__} wrong at input {bad:#x}: "
                f"got {int(got[bad]):#x}, want {int(want[bad]):#x}"
            )


_verify()
