"""Vectorized AES-256 (encrypt-only) over numpy uint8 batches.

Bit-exact with ``dcf_tpu.spec.aes256_encrypt_block`` (and FIPS-197); used by
the host-side batched keygen and the numpy eval oracle.  The JAX twin lives in
``dcf_tpu.ops.aes_jax``.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.spec import AES_SBOX, SHIFT_ROWS, aes256_expand_key

__all__ = ["SBOX_NP", "SHIFT_ROWS_NP", "expand_key_np", "aes256_encrypt_np"]

SBOX_NP = np.frombuffer(AES_SBOX, dtype=np.uint8).copy()
SHIFT_ROWS_NP = np.array(SHIFT_ROWS, dtype=np.int64)


def expand_key_np(key: bytes) -> np.ndarray:
    """32-byte key -> round keys as a uint8 array of shape [15, 16]."""
    return np.array(
        [np.frombuffer(rk, dtype=np.uint8) for rk in aes256_expand_key(key)]
    )


def _xtime(a: np.ndarray) -> np.ndarray:
    """GF(2^8) multiply-by-2 on uint8 arrays."""
    return (((a.astype(np.uint16) << 1) ^ np.where(a & 0x80, 0x1B, 0)) & 0xFF).astype(
        np.uint8
    )


def aes256_encrypt_np(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """Encrypt a batch of 16-byte blocks.

    round_keys: uint8 [15, 16]; blocks: uint8 [..., 16] -> uint8 [..., 16].
    """
    s = blocks ^ round_keys[0]
    for rnd in range(1, 14):
        s = SBOX_NP[s]
        s = s[..., SHIFT_ROWS_NP]
        a = s.reshape(*s.shape[:-1], 4, 4)
        a0, a1, a2, a3 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
        mixed = np.stack(
            [
                _xtime(a0) ^ _xtime(a1) ^ a1 ^ a2 ^ a3,
                a0 ^ _xtime(a1) ^ _xtime(a2) ^ a2 ^ a3,
                a0 ^ a1 ^ _xtime(a2) ^ _xtime(a3) ^ a3,
                _xtime(a0) ^ a0 ^ a1 ^ a2 ^ _xtime(a3),
            ],
            axis=-1,
        )
        s = mixed.reshape(*blocks.shape) ^ round_keys[rnd]
    s = SBOX_NP[s]
    s = s[..., SHIFT_ROWS_NP]
    return s ^ round_keys[14]
