"""Mod-2^w lane accumulation for the device value paths.

The additive output groups (``spec.GROUPS``) read the ``lam`` payload
bytes as little-endian w-bit lanes.  The device backends never hold the
payload as bytes — they hold bit planes — so the group add must run in
the plane domain.  Two layouts exist:

* **byte-major** (``utils.bits.byte_bits_lsb``): plane ``p = byte*8 +
  bit``, bits LSB-first.  A little-endian lane ``l`` therefore occupies
  the w consecutive planes ``[l*w, (l+1)*w)`` in exact carry order, so
  the add is a ripple carry along the plane axis: ``w`` steps, each a
  handful of word-ops on a full ``[L, ...]`` plane slab, bitwise-parallel
  across the 32 points packed per lane word.  Used by the bitsliced /
  keylanes XLA cores (planes ``[8*lam, K, W]``).

* **bit-major** (``utils.bits.bitmajor_perm``, lam = 16 only): plane
  ``p' = bit*16 + byte`` — rows ``[16j, 16j+16)`` hold bit ``j`` of all
  16 byte positions.  A lane's bits are strided, so the ripple runs as
  ``w/8`` passes over the 8 bit-layers: within a pass carries ripple bit
  ``j -> j+1`` of every byte at once (one ``[16, W]`` slab per step), and
  between passes the byte-boundary carry moves to the next byte position
  by a static row shift (slice + concat — the same primitive the prefix
  kernel's butterfly transpose uses, so it lowers in Mosaic and the
  interpreter alike).  Entry carries converge after ``w/8`` passes; the
  total step count equals the straight ripple's.

The party sign ``(-1)^b`` of the additive eval never enters the kernels:
it factors out of every level, so kernels accumulate unsigned and the
backend negates party 1's result once at the output edge
(``planes_neg_*`` — two's complement: NOT then +1 per lane, one extra
ripple).

All helpers are group-width generic (w in {8, 16, 32}), dtype-agnostic
over int32/uint32 plane words, and pure jnp — usable inside Pallas
kernels and plain XLA jits alike.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dcf_tpu.spec import GROUP_WIDTH

__all__ = [
    "group_width",
    "planes_add_bytemajor",
    "planes_sub_bytemajor",
    "planes_neg_bytemajor",
    "planes_add_bitmajor16",
    "planes_neg_bitmajor16",
    "jnp_bytes_to_lanes",
    "jnp_lanes_to_bytes",
]


def group_width(group: str) -> int:
    """Lane width in bits of an additive group (0 for xor)."""
    return GROUP_WIDTH.get(group, 0)


# -- byte-major layout (planes [8*lam, ...], p = byte*8 + bit) ---------------


def planes_add_bytemajor(x, y, w: int, *, carry_in: bool = False):
    """Per-lane ``x + y mod 2^w`` on byte-major plane slabs.

    ``x``/``y``: plane words ``[8*lam, ...]`` (any trailing shape); plane
    ``l*w + k`` is bit ``k`` of lane ``l``.  ``carry_in`` adds 1 to every
    lane (the two's-complement tail of subtraction).
    """
    xk0 = x[0::w]
    c = ~jnp.zeros_like(xk0) if carry_in else jnp.zeros_like(xk0)
    outs = []
    for k in range(w):
        xk = x[k::w]
        yk = y[k::w]
        axb = xk ^ yk
        outs.append(axb ^ c)
        if k + 1 < w:
            c = (xk & yk) | (c & axb)
    # outs[k] holds planes l*w + k: interleave back to plane order.
    return jnp.stack(outs, axis=1).reshape(x.shape)


def planes_sub_bytemajor(x, y, w: int):
    """Per-lane ``x - y mod 2^w`` (add the complement with carry-in)."""
    return planes_add_bytemajor(x, ~y, w, carry_in=True)


def planes_neg_bytemajor(x, w: int):
    """Per-lane ``-x mod 2^w`` (two's complement)."""
    return planes_add_bytemajor(~x, jnp.zeros_like(x), w, carry_in=True)


# -- bit-major layout (lam = 16: planes [128, W], p' = bit*16 + byte) --------


def planes_add_bitmajor16(x, y, w: int, *, carry_in: bool = False):
    """Per-lane ``x + y mod 2^w`` on bit-major plane blocks ``[128, W]``.

    Lane ``l`` spans bytes ``[l*step, (l+1)*step)`` (step = w/8); bit
    ``j`` of byte ``B`` sits at row ``j*16 + B``.  Runs ``step`` passes
    over the 8 bit-layers; byte-boundary carries move down one row
    between passes (masked at lane starts, where ``carry_in`` enters
    instead).
    """
    step = w // 8
    byte_idx = jax.lax.broadcasted_iota(jnp.int32, (16, 1), 0)
    lane_start = jnp.where(byte_idx % step == 0, jnp.int32(-1),
                           jnp.int32(0)).astype(x.dtype)
    cin = (lane_start if carry_in else jnp.zeros_like(lane_start))
    xl = [x[16 * j:16 * j + 16] for j in range(8)]
    yl = [y[16 * j:16 * j + 16] for j in range(8)]
    entry = cin * jnp.ones_like(xl[0])
    outs = xl
    for _ in range(step):
        c = entry
        outs = []
        for j in range(8):
            axb = xl[j] ^ yl[j]
            outs.append(axb ^ c)
            c = (xl[j] & yl[j]) | (c & axb)
        if step == 1:
            break
        # Carry out of bit 7 of byte B enters bit 0 of byte B+1 (static
        # row shift), except at lane starts, which re-receive carry_in.
        shifted = jnp.concatenate([jnp.zeros_like(c[:1]), c[:15]], axis=0)
        entry = (shifted & ~lane_start) | cin
    return jnp.concatenate(outs, axis=0)


def planes_neg_bitmajor16(x, w: int):
    """Per-lane ``-x mod 2^w`` on bit-major plane blocks ``[128, W]``."""
    return planes_add_bitmajor16(~x, jnp.zeros_like(x), w, carry_in=True)


# -- byte <-> lane conversion for the byte-level jnp walk --------------------


def jnp_bytes_to_lanes(x, w: int):
    """uint8 ``[..., lam]`` -> unsigned w-bit lanes ``[..., 8*lam/w]``.

    Explicit little-endian assembly (no bitcast), so the result is
    platform-independent and matches ``spec.bytes_to_lanes``.
    """
    step = w // 8
    dt = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32}[w]
    g = x.reshape(*x.shape[:-1], x.shape[-1] // step, step).astype(dt)
    shifts = jnp.arange(step, dtype=dt) * dt(8)
    return jnp.sum(g << shifts, axis=-1, dtype=dt) if step > 1 else g[..., 0]


def jnp_lanes_to_bytes(lanes, w: int):
    """Inverse of :func:`jnp_bytes_to_lanes` -> uint8 ``[..., lam]``."""
    step = w // 8
    if step == 1:
        return lanes.astype(jnp.uint8)
    shifts = jnp.arange(step, dtype=lanes.dtype) * jnp.asarray(
        8, dtype=lanes.dtype)
    b = (lanes[..., None] >> shifts).astype(jnp.uint8)
    return b.reshape(*lanes.shape[:-1], lanes.shape[-1] * step)
