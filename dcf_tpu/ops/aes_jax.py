"""AES-256 (encrypt-only) in JAX, bit-exact with the spec/numpy versions.

``aes256_encrypt_jax`` uses a table S-box via ``jnp.take`` (one 256-entry
gather per round) — simple, and the parity anchor for any faster variant.

All arithmetic is uint8; XLA maps it onto the VPU.  Round keys are expanded
on the host (``dcf_tpu.ops.aes.expand_key_np``) and passed in as a [15, 16]
uint8 array — the per-level key schedule never runs on device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dcf_tpu.ops.aes import SBOX_NP, SHIFT_ROWS_NP

__all__ = ["aes256_encrypt_jax"]

def _tables() -> tuple[jnp.ndarray, jnp.ndarray]:
    # Built per call, never at module scope or cached: a module-scope
    # jnp.asarray would initialize the JAX backend at import time
    # (jax.distributed.initialize in parallel/_compat must precede ANY
    # computation), and a cache primed inside a jit/scan trace would
    # leak that trace's constant tracer into every later trace.  Under
    # jit these are folded constants; the eager cost is a 272-byte put.
    return jnp.asarray(SBOX_NP), jnp.asarray(SHIFT_ROWS_NP)


def _xtime(a: jnp.ndarray) -> jnp.ndarray:
    # uint8 left-shift wraps mod 256, which is exactly (a << 1) & 0xFF.
    return (a << 1) ^ ((a >> 7) * jnp.uint8(0x1B))


def aes256_encrypt_jax(round_keys: jnp.ndarray, blocks: jnp.ndarray) -> jnp.ndarray:
    """Encrypt uint8 blocks [..., 16] under round_keys uint8 [15, 16]."""
    sbox_j, shift_j = _tables()
    s = blocks ^ round_keys[0]
    for rnd in range(1, 14):
        s = jnp.take(sbox_j, s)
        s = s[..., shift_j]
        a = s.reshape(*s.shape[:-1], 4, 4)
        a0, a1, a2, a3 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
        mixed = jnp.stack(
            [
                _xtime(a0) ^ _xtime(a1) ^ a1 ^ a2 ^ a3,
                a0 ^ _xtime(a1) ^ _xtime(a2) ^ a2 ^ a3,
                a0 ^ a1 ^ _xtime(a2) ^ _xtime(a3) ^ a3,
                _xtime(a0) ^ a0 ^ a1 ^ a2 ^ _xtime(a3),
            ],
            axis=-1,
        )
        s = mixed.reshape(*blocks.shape) ^ round_keys[rnd]
    s = jnp.take(sbox_j, s)
    s = s[..., shift_j]
    return s ^ round_keys[14]
