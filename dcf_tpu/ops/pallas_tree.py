"""Pallas TPU kernel for breadth-first (tree) full-domain DCF evaluation.

The walk backends evaluate each point's n-level path independently — for
the full domain that is n * 2^n PRG calls.  But the 2^n evaluation paths
form the GGM tree: expanding the tree level by level costs only
sum_i 2^i ≈ 2^{n+1} PRG calls, ~n/2 x less work (the classic FSS
full-domain-eval optimization; the reference crate has no analog and
would pay the full walk cost, src/lib.rs:163-204).

One kernel application = one level: a tile of parent nodes (packed 32 per
uint32 lane word, bit-major planes like ops.pallas_eval) expands into its
left- and right-child tiles with the correction word applied and the
value accumulator pushed down both branches:

    v_child = v_parent ^ v_hat_dir ^ (t_parent & cw_v)      (lib.rs:181-189)
    s/t children per lib.rs:177-180

Levels double the arrays as [all-left-children ; all-right-children], so
leaf array position p holds domain point bitreverse_n(p) — consumers
account for it arithmetically (dcf_tpu.backends.fulldomain).

The top of the tree (< 2^k0 nodes) is host-expanded (tiny and irregular);
the device runs levels k0..n-1, which hold ~100% of the work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_planes_bitmajor,
    aes_walk_cipher_v3,
    prep_rk_bitmajor_v3,
)
from dcf_tpu.ops.group_accum import group_width, planes_add_bitmajor16

__all__ = ["tree_expand_device", "tree_expand_raw"]


def _expand_kernel(rk_ref, cs_ref, cv_ref, ct_ref, s_ref, v_ref, t_ref,
                   sl_o, vl_o, tl_o, sr_o, vr_o, tr_o, *, interpret: bool,
                   group: str = "xor"):
    ones = jnp.int32(-1)
    gw = group_width(group)
    rk = rk_ref[:]
    if interpret:
        def aes(state):
            return aes256_encrypt_planes_bitmajor(jnp, rk, state, ones)
    else:
        rk_p = prep_rk_bitmajor_v3(jnp, rk)

        def aes(state):
            return aes_walk_cipher_v3(jnp, rk_p, state, ones)

    plane_idx = jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0)
    lbm = jnp.where(plane_idx == 15, jnp.int32(0), ones)

    wt = s_ref.shape[1]
    s = s_ref[:]
    v = v_ref[:]
    t = t_ref[:]  # [1, wt]
    sp = s ^ ones
    enc = aes(jnp.concatenate([s, sp], axis=1))
    sl_raw = enc[:, :wt] ^ s
    vl_raw = enc[:, wt:] ^ sp
    t_l = sl_raw[0:1, :]
    t_r = vl_raw[0:1, :]
    csg = cs_ref[:] & t
    cvg = cv_ref[:] & t
    sl_o[:] = (sl_raw & lbm) ^ csg
    sr_o[:] = (s & lbm) ^ csg
    tl_o[:] = t_l ^ (t & ct_ref[0])
    tr_o[:] = t_r ^ (t & ct_ref[1])
    if gw:
        # Additive groups: the accumulator pushed down both branches is
        # an UNSIGNED per-lane sum (the party sign factors out of the
        # whole walk and is applied once at the consumer's output edge).
        vl_o[:] = planes_add_bitmajor16(
            v, planes_add_bitmajor16(vl_raw & lbm, cvg, gw), gw)
        vr_o[:] = planes_add_bitmajor16(
            v, planes_add_bitmajor16(sp & lbm, cvg, gw), gw)
    else:
        vl_o[:] = v ^ (vl_raw & lbm) ^ cvg
        vr_o[:] = v ^ (sp & lbm) ^ cvg


def _expand_level(rk, cs, cv, ct, s, v, t, *, interpret: bool,
                  group: str = "xor"):
    """One tree level: [128, W] parents -> six [.., W] child halves."""
    w = s.shape[1]
    wt = min(128, w)
    grid = (w // wt,)
    state_spec = pl.BlockSpec((128, wt), lambda j: (0, j))
    t_spec = pl.BlockSpec((1, wt), lambda j: (0, j))
    return pl.pallas_call(
        partial(_expand_kernel, interpret=interpret, group=group),
        out_shape=(
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 1), lambda j: (0, 0, 0)),
            pl.BlockSpec((128, 1), lambda j: (0, 0)),
            pl.BlockSpec((128, 1), lambda j: (0, 0)),
            pl.BlockSpec((2,), lambda j: (0,), memory_space=pltpu.SMEM),
            state_spec, state_spec, t_spec,
        ],
        out_specs=(state_spec, state_spec, t_spec,
                   state_spec, state_spec, t_spec),
        interpret=interpret,
    )(rk, cs, cv, ct, s, v, t)


@partial(jax.jit, static_argnames=("k0", "k1", "interpret", "group"))
def tree_expand_raw(rk, cw_s_t, cw_v_t, cw_t_pm, s, v, t,
                    k0: int, k1: int, interpret: bool = False,
                    group: str = "xor"):
    """Expand levels k0..k1-1 WITHOUT finalizing: returns the raw
    (s, v, t) node planes at level k1 (int32 [128, 2^k1 / 32] x2 +
    [1, 2^k1 / 32]), leaf order bitreverse_k1.

    This is the frontier the prefix-sharing evaluator
    (ops.pallas_prefix / backends.pallas_prefix) gathers per-point
    carries from: a batch of M random points shares the top ~log2(M)
    walk levels, so expanding them once as a tree (~2 PRG calls per
    node) replaces M per-point PRG calls per level.
    """
    for i in range(k0, k1):
        s_l, v_l, t_l, s_r, v_r, t_r = _expand_level(
            rk, cw_s_t[i], cw_v_t[i], cw_t_pm[i], s, v, t,
            interpret=interpret, group=group)
        s = jnp.concatenate([s_l, s_r], axis=1)
        v = jnp.concatenate([v_l, v_r], axis=1)
        t = jnp.concatenate([t_l, t_r], axis=1)
    return s, v, t


@partial(jax.jit, static_argnames=("k0", "n", "interpret"))
def tree_expand_device(rk, cw_s_t, cw_v_t, cw_t_pm, cw_np1_t, s, v, t,
                       k0: int, n: int, interpret: bool = False):
    """Expand levels k0..n-1 and finalize leaves.

    rk int32 [15, 128, 1]; cw_s_t/cw_v_t int32 [n, 128, 1] bit-major CW
    plane masks; cw_t_pm int32 [n, 2] (0/-1); cw_np1_t int32 [128, 1];
    s/v int32 [128, 2^k0 / 32], t int32 [1, 2^k0 / 32] — the level-k0
    state in leaf order (position = bitreverse of the k0-bit prefix).
    Returns y planes int32 [128, 2^n / 32], leaf order bitreverse_n.
    """
    s, v, t = tree_expand_raw(rk, cw_s_t, cw_v_t, cw_t_pm, s, v, t,
                              k0=k0, k1=n, interpret=interpret)
    return v ^ s ^ (cw_np1_t & t)
