"""Batched Hirose PRG over numpy uint8 arrays — and the ``Prg`` protocol.

Bit-exact with ``dcf_tpu.spec.HirosePrgSpec`` (reference src/prg.rs:42-73),
vectorized over an arbitrary leading batch shape.  One ``gen`` call expands a
batch of seeds into left/right child ``(s, v, t)`` triples.

The Prg protocol (reference ``trait Prg``, src/lib.rs:52-58)
------------------------------------------------------------

The reference's most important architectural seam is its PRG trait: the GGM
walk (gen and eval) is generic over any length-doubling PRG.  Here the seam
is a structural protocol rather than a nominal type, at three levels, all
expressing the same contract:

* **spec level** (bytes): an object with ``.lam`` and
  ``.gen(seed: bytes[lam]) -> [(s_l, v_l, t_l), (s_r, v_r, t_r)]`` where
  s/v are ``bytes[lam]`` and t is ``bool`` — consumed by ``spec.gen`` /
  ``spec.eval_point``.
* **batched host level** (numpy): an object with ``.lam`` and
  ``.gen(seeds: uint8[..., lam]) -> PrgOut`` (this module's dataclass; t
  fields are uint8 in {0, 1}) — consumed by ``dcf_tpu.gen.gen_batch`` and
  ``backends.numpy_backend.eval_batch_np``.
* **device level** (jax): a module-level function
  ``(round_keys, lam, seeds uint8[..., lam]) -> (s_l, v_l, t_l, s_r, v_r,
  t_r)`` — consumed by ``backends.jax_backend.eval_core`` (``prg_fn=``).

Requirements on an implementation: pure/deterministic in the seed; the four
s/v outputs are ``lam`` bytes each; the two t-bits may depend on the seed
arbitrarily.  Everything else (child selection, correction words, the
two-party invariant) is the walk's job and works for ANY such PRG — proven
by ``tests/mock_prg.py``, a trivially-fast non-cryptographic implementation
wired through spec gen/eval, ``gen_batch``, ``eval_batch_np`` and
``JaxBackend`` in ``tests/test_prg_seam.py``.

What is NOT behind the seam: the compiled hot paths (the Pallas kernels,
the bitsliced XLA backend, the C++ core) specialize the Hirose AES-256
construction at the bit-plane level for performance, exactly as the
reference's only shipped PRG is that construction; their outputs are
checked bit-identical against the generic paths above, so the seam plus
the parity matrix covers them transitively.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from dcf_tpu.ops.aes import aes256_encrypt_np, expand_key_np
from dcf_tpu.spec import hirose_used_cipher_indices

__all__ = ["PrgOut", "HirosePrgNp"]


@dataclass(frozen=True)
class PrgOut:
    """PRG expansion of a seed batch: left/right child (s, v, t) triples.

    Shapes for a seed batch [..., lam]: s/v are uint8 [..., lam], t is
    uint8 [...] with values in {0, 1}.
    """

    s_l: np.ndarray
    v_l: np.ndarray
    t_l: np.ndarray
    s_r: np.ndarray
    v_r: np.ndarray
    t_r: np.ndarray


class HirosePrgNp:
    """Numpy twin of ``spec.HirosePrgSpec`` (same key-count contract).

    ``mask=False`` skips the final 8*lam-1-bit clearing (src/prg.rs:65-68)
    — used by the large-lambda hybrid evaluator, whose narrow 32-byte walk
    replicates the first two blocks of a bigger PRG whose masked byte lies
    in the wide region (backends.large_lambda).
    """

    def __init__(self, lam: int, keys: Sequence[bytes], mask: bool = True,
                 warn: bool = True):
        self.lam = lam
        self.mask = mask
        # warn=False marks internal constructions (the hybrid evaluator's
        # narrow sub-walk of a larger contract-conforming shape) that are
        # not user API edges.
        used = hirose_used_cipher_indices(lam, len(keys), warn=warn)
        self.round_keys = {i: expand_key_np(keys[i]) for i in used}

    def gen(self, seeds: np.ndarray) -> PrgOut:
        lam = self.lam
        assert seeds.dtype == np.uint8 and seeds.shape[-1] == lam
        seed_p = seeds ^ np.uint8(0xFF)
        batch = seeds.shape[:-1]
        buf0 = np.zeros((*batch, 2, lam), dtype=np.uint8)
        buf1 = np.zeros((*batch, 2, lam), dtype=np.uint8)
        # Truncated encryption loop: only block positions k = 0..min(2, lam/16)
        # with cipher index 17*k are encrypted (src/prg.rs:48-56).
        for k in range(min(2, lam // 16)):
            rk = self.round_keys[17 * k]
            lo, hi = 16 * k, 16 * (k + 1)
            buf0[..., k, lo:hi] = aes256_encrypt_np(rk, seeds[..., lo:hi])
            buf1[..., k, lo:hi] = aes256_encrypt_np(rk, seed_p[..., lo:hi])
        # Feed-forward into both halves (src/prg.rs:57-62).
        buf0 ^= seeds[..., None, :]
        buf1 ^= seed_p[..., None, :]
        # t-bits from half-0 buffers before masking (src/prg.rs:63-64).
        t_l = buf0[..., 0, 0] & np.uint8(1)
        t_r = buf1[..., 0, 0] & np.uint8(1)
        # Clear LSB of the last byte of all four outputs (src/prg.rs:65-68).
        if self.mask:
            buf0[..., lam - 1] &= np.uint8(0xFE)
            buf1[..., lam - 1] &= np.uint8(0xFE)
        return PrgOut(
            s_l=buf0[..., 0, :],
            v_l=buf1[..., 0, :],
            t_l=t_l,
            s_r=buf0[..., 1, :],
            v_r=buf1[..., 1, :],
            t_r=t_r,
        )
