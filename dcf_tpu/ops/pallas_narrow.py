"""Pallas kernel for the hybrid evaluator's NARROW walk (lam >= 48).

The large-lambda hybrid split (backends.large_lambda) reduces a lam-byte
evaluation to a 32-byte walk plus a GF(2) matmul; this kernel is that
32-byte walk, fused in VMEM like the flagship lam=16 kernel
(ops.pallas_eval) — it replaced an XLA plane path that was 82% of the
hybrid's runtime.

Narrow PRG dataflow (the first two blocks of a big-lambda Hirose PRG,
reference src/prg.rs:48-62; identical to lam=32 except NO final-bit mask
— the big PRG's masked byte is wide):

    cipher 0  encrypts (s_b0, ~s_b0): left child's block 0 (s and v)
    cipher 17 encrypts (s_b1, ~s_b1): RIGHT child's block 1
    all other child blocks are feed-forward copies:
        left  = (E0(s_b0)^s_b0,  s_b1)        right = (s_b0, E17(s_b1)^s_b1)
        v_l   = (E0(~s_b0)^~s_b0, ~s_b1)      v_r   = (~s_b0, E17(~s_b1)^~s_b1)
    t_l / t_r = bit 0 of byte 0 of the two block-0 outputs

State per DCF block is a separate [128, wt] bit-major plane tile; the 4
AES encryptions per level run as ONE cipher application over [128, 4*wt]
with lane-dependent round keys (cipher 0 on the first half, cipher 17 on
the second).  Besides the two y blocks the kernel emits the t-bit
TRAJECTORY (the gate bit of every level plus the final bit) — the wide
part is an affine function of exactly that.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops._compat import CompilerParams as _CompilerParams

from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_planes_bitmajor,
    aes_walk_cipher_v3,
    prep_rk_bitmajor_v3,
)

__all__ = ["dcf_narrow_walk_pallas", "make_narrow_aes",
           "narrow_prg_expand", "narrow_walk_levels"]


def make_narrow_aes(rk2_ref, wt: int, interpret: bool):
    """The narrow walk's per-grid-step AES closure: ONE cipher application
    over [128, 4*wt] with lane-dependent round keys (cipher 0 over lanes
    [0, 2wt), cipher 17 over [2wt, 4wt)).  rk2_ref is [15, 128, 2];
    expanded once per grid step.  Interpret mode keeps the compact v1
    graph (same rule as ops.pallas_eval.make_aes)."""
    ones = jnp.int32(-1)
    z2 = jnp.zeros((15, 128, 2 * wt), jnp.int32)
    rk_wide = jnp.concatenate(
        [rk2_ref[:, :, 0:1] ^ z2, rk2_ref[:, :, 1:2] ^ z2], axis=2)
    if interpret:
        def aes(state):
            # v1 path with per-lane keys: ARK via the wide masks
            return aes256_encrypt_planes_bitmajor(
                jnp, rk_wide, state, ones)
        return aes
    rk_p = prep_rk_bitmajor_v3(jnp, rk_wide)

    def aes(state):
        return aes_walk_cipher_v3(jnp, rk_p, state, ones)
    return aes


def narrow_prg_expand(aes, s0, s1):
    """One party's narrow Hirose PRG expansion on packed two-block planes
    — the per-level AES core shared by the eval walk
    (``narrow_walk_levels``) and the device keygen
    (``ops.pallas_keygen``), so gen and eval cannot drift apart at the
    cipher layer.

    ``s0``/``s1``: the party's block-0/block-1 seed planes [128, wt].
    ONE ``aes`` application (``make_narrow_aes``: cipher 0 over the
    first 2*wt lanes, cipher 17 over the last) covers all four
    encryptions of the level.  Returns
    ``(e_s0, e_v0, e_s1, e_v1, sp0, sp1, t_l, t_r)`` where the child
    triples assemble as (reference src/prg.rs:48-62):

        left  s = (e_s0, s1)    left  v = (e_v0, sp1)
        right s = (s0, e_s1)    right v = (sp0, e_v1)

    and ``t_l``/``t_r`` are the [1, wt] t-bit planes (bit 0 of byte 0 of
    the block-0 s/v outputs, src/prg.rs:63-64).  No final-bit masking:
    the big PRG's masked byte is wide (module docstring)."""
    ones = jnp.int32(-1)
    wt = s0.shape[1]
    sp0 = s0 ^ ones
    sp1 = s1 ^ ones
    enc = aes(jnp.concatenate([s0, sp0, s1, sp1], axis=1))
    e_s0 = enc[:, :wt] ^ s0           # left child block 0 (s)
    e_v0 = enc[:, wt:2 * wt] ^ sp0    # left child block 0 (v)
    e_s1 = enc[:, 2 * wt:3 * wt] ^ s1  # RIGHT child block 1 (s)
    e_v1 = enc[:, 3 * wt:] ^ sp1      # right child block 1 (v)
    return e_s0, e_v0, e_s1, e_v1, sp0, sp1, e_s0[0:1, :], e_v0[0:1, :]


def narrow_walk_levels(aes, sa, sb, t, va, vb, cs0_ref, cs1_ref, cv0_ref,
                       cv1_ref, cw_t_ref, xm_ref, tr_ref, n: int):
    """The n-level NARROW walk loop on packed two-block planes, shared by
    the from-root kernel below and the hybrid-prefix kernels
    (ops.pallas_hybrid_prefix).  The cw/xm refs are indexed [0, i] per
    level i in 0..n-1; the GATE bit of every level plus the final t are
    written to ``tr_ref`` (n+1 entries).  Returns the final carry
    (sa, sb, t, va, vb)."""
    ones = jnp.int32(-1)

    def level(i, carry):
        sa, sb, t, va, vb = carry
        tr_ref[0, pl.dslice(i, 1)] = t  # emit the GATE bit of this level
        e_sa, e_va, e_sb, e_vb, spa, spb, t_l, t_r = narrow_prg_expand(
            aes, sa, sb)

        cs0 = cs0_ref[0, i]  # [128, 1] per level
        cs1 = cs1_ref[0, i]
        cv0 = cv0_ref[0, i]
        cv1 = cv1_ref[0, i]
        ctl = cw_t_ref[0, i, 0]
        ctr = cw_t_ref[0, i, 1]
        cs0g = cs0 & t
        cs1g = cs1 & t
        # children (block0, block1) with CW correction
        sl0, sl1 = e_sa ^ cs0g, sb ^ cs1g
        sr0, sr1 = sa ^ cs0g, e_sb ^ cs1g
        vl0, vl1 = e_va, spb
        vr0, vr1 = spa, e_vb
        t_l = t_l ^ (t & ctl)
        t_r = t_r ^ (t & ctr)

        xm = xm_ref[0, i]  # [1, wt]
        nxm = xm ^ ones
        va = va ^ (vr0 & xm) ^ (vl0 & nxm) ^ (cv0 & t)
        vb = vb ^ (vr1 & xm) ^ (vl1 & nxm) ^ (cv1 & t)
        sa = (sr0 & xm) | (sl0 & nxm)
        sb = (sr1 & xm) | (sl1 & nxm)
        t = (t_r & xm) | (t_l & nxm)
        return (sa, sb, t, va, vb)

    carry = jax.lax.fori_loop(0, n, level, (sa, sb, t, va, vb))
    tr_ref[0, pl.dslice(n, 1)] = carry[2]
    return carry


def _kernel(rk2_ref, s0a_ref, s0b_ref, cs0_ref, cs1_ref, cv0_ref, cv1_ref,
            np1a_ref, np1b_ref, cw_t_ref, xm_ref,
            y0_ref, y1_ref, tr_ref, *, b: int, n: int, interpret: bool):
    wt = xm_ref.shape[3]
    ones = jnp.int32(-1)
    aes = make_narrow_aes(rk2_ref, wt, interpret)

    z = jnp.zeros((128, wt), jnp.int32)
    sa = s0a_ref[0] ^ z  # block 0 seed planes
    sb = s0b_ref[0] ^ z  # block 1
    t = jnp.full((1, wt), ones if b else jnp.int32(0), jnp.int32)

    sa, sb, t, va, vb = narrow_walk_levels(
        aes, sa, sb, t, z, z, cs0_ref, cs1_ref, cv0_ref, cv1_ref,
        cw_t_ref, xm_ref, tr_ref, n)
    y0_ref[0] = va ^ sa ^ (np1a_ref[0] & t)
    y1_ref[0] = vb ^ sb ^ (np1b_ref[0] & t)


def dcf_narrow_walk_pallas(
    rk2,      # int32 [15, 128, 2]   bit-major round keys (ciphers 0, 17)
    s0a, s0b,  # int32 [K, 128, 1]   seed planes per narrow block
    cs0, cs1,  # int32 [K, n, 128, 1]  CW seed planes per block
    cv0, cv1,  # int32 [K, n, 128, 1]  CW value planes per block
    np1a, np1b,  # int32 [K, 128, 1]  final CW planes per block
    cw_t,     # int32 [K, n, 2]      (tl, tr) 0/-1
    x_mask,   # int32 [1, n, 1, W]   walk-order input-bit masks (shared)
    *,
    b: int,
    tile_words: int = 128,
    interpret: bool = False,
):
    """Narrow walk for party ``b``: returns (y_block0 [K, 128, W],
    y_block1 [K, 128, W], trajectory [K, n+1, W])."""
    k_num = s0a.shape[0]
    n = cs0.shape[1]
    w = x_mask.shape[3]
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"point words {w} not a multiple of tile {wt}")

    grid = (k_num, w // wt)
    keyed = pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0))
    level_spec = pl.BlockSpec((1, n, 128, 1), lambda k, j: (k, 0, 0, 0))
    state_out = pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j))
    # At many keys x few point-words Mosaic's whole-call staging exceeds
    # the default 16MB scoped-vmem budget even though each grid step's
    # blocks are tiny; raise the limit toward the chip's physical VMEM.
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_kernel, b=b, n=n, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, n + 1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda k, j: (0, 0, 0)),
            keyed, keyed,
            level_spec, level_spec, level_spec, level_spec,
            keyed, keyed,
            pl.BlockSpec((1, n, 2), lambda k, j: (k, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n, 1, wt), lambda k, j: (0, 0, 0, j)),
        ],
        out_specs=(
            state_out, state_out,
            pl.BlockSpec((1, n + 1, wt), lambda k, j: (k, 0, j)),
        ),
        interpret=interpret,
    )(rk2, s0a, s0b, cs0, cs1, cv0, cv1, np1a, np1b, cw_t, x_mask)
