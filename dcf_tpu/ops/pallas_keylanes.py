"""Pallas TPU kernel for the many-keys DCF walk (keys packed in lanes).

The secure-ReLU regime (BASELINE config 5: 10^6 keys x 10^3 shared points)
is the dual of the flagship batch-eval shape: keys ride the lane axis
(32 per uint32 word) so the per-key correction words are PACKED DATA — one
word of cw planes corrects 32 keys — while the shared points batch on the
sublane axis.  The XLA keylanes path (backends.jax_bitsliced.
eval_core_keylanes) round-trips multi-GB plane intermediates through HBM
every level; this kernel keeps the (s, t, v) carry for a
(m_tile x kw_tile) tile in VMEM across a whole chunk of levels.

Reference semantics: /root/reference/src/lib.rs:163-204, src/prg.rs:42-73.

Shapes (lam = 16, n levels, M shared points, Kw = keys/32 words):

    s, v      int32 [128, M, Kw]   bit-major planes (p' = bit*16 + byte)
    t         int32 [M, Kw]        per-(point, key-lane) control bits
    cw_s/cw_v int32 [n, 128, Kw]   packed per-key correction planes
    cw_tl/tr  int32 [n, Kw]        packed per-key t-correction bits
    x_mask    int32 [n, M, 1]      walk-order input-bit masks (0 / -1),
                                   shared across keys (trailing 1 so the
                                   point tile rides the sublane block dim)

The n-level walk runs as ceil(n / level_chunk) pallas_calls; each call's
grid is (Kw/kw_tile, M/m_tile) with the level loop INSIDE the kernel, so
the carry round-trips HBM only once per level chunk (VMEM cannot hold all
n levels' correction slabs at once — 2 x 8 MB at n=128/Kw-tile=128).
The point-tile grid axis is innermost, so Pallas reuses each key tile's
correction slab across all point tiles without re-fetching.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_planes_bitmajor,
    aes_walk_cipher_v3,
    prep_rk_bitmajor_v3,
)

__all__ = ["dcf_eval_keylanes_pallas"]


def _kernel(rk_ref, s_ref, t_ref, v_ref, cw_s_ref, cw_v_ref, cw_tl_ref,
            cw_tr_ref, xm_ref, so_ref, to_ref, vo_ref, *,
            lc: int, interpret: bool):
    ones = jnp.int32(-1)
    rk = rk_ref[:]
    if interpret:
        def aes(state):
            shp = state.shape
            return aes256_encrypt_planes_bitmajor(
                jnp, rk, state.reshape(128, -1), ones).reshape(shp)
    else:
        rk_p = prep_rk_bitmajor_v3(jnp, rk)

        def aes(state):
            return aes_walk_cipher_v3(jnp, rk_p, state, ones)

    # PRG mask: bit-major plane 15 (byte 15 bit 0) is cleared
    # (reference src/prg.rs:65-68).
    plane_idx = jax.lax.broadcasted_iota(jnp.int32, (128, 1, 1), 0)
    lbm = jnp.where(plane_idx == 15, jnp.int32(0), ones)

    kw = s_ref.shape[-1]

    def level(l, carry):
        s, t, v = carry
        sp = s ^ ones
        enc = aes(jnp.concatenate([s, sp], axis=-1))
        sl_raw = enc[..., :kw] ^ s
        vl_raw = enc[..., kw:] ^ sp
        t_l = sl_raw[0]  # plane 0: [m_tile, kw] lane masks
        t_r = vl_raw[0]
        s_l = sl_raw & lbm
        v_l = vl_raw & lbm
        s_r = s & lbm
        v_r = sp & lbm

        cs = cw_s_ref[l][:, None, :]   # [128, 1, kw]
        cv = cw_v_ref[l][:, None, :]
        ctl = cw_tl_ref[l]             # [kw]
        ctr = cw_tr_ref[l]
        gate = t[None, :, :]
        s_l = s_l ^ (cs & gate)
        s_r = s_r ^ (cs & gate)
        t_l = t_l ^ (t & ctl[None, :])
        t_r = t_r ^ (t & ctr[None, :])

        xm = xm_ref[l]                 # [m_tile, 1]
        xm_c = xm                      # broadcast over key lanes
        xm_p = xm[None]                # broadcast over planes + key lanes
        nxm_c = xm_c ^ ones
        nxm_p = xm_p ^ ones
        v = v ^ (v_r & xm_p) ^ (v_l & nxm_p) ^ (cv & gate)
        s = (s_r & xm_p) | (s_l & nxm_p)
        t = (t_r & xm_c) | (t_l & nxm_c)
        return (s, t, v)

    s, t, v = jax.lax.fori_loop(
        0, lc, level, (s_ref[:], t_ref[:], v_ref[:]))
    so_ref[:] = s
    to_ref[:] = t
    vo_ref[:] = v


def dcf_eval_keylanes_pallas(
    rk,        # int32 [15, 128, 1]   bit-major round-key masks
    s0_t,      # int32 [128, Kw]      party seed planes (bit-major)
    cw_s_t,    # int32 [n, 128, Kw]   packed CW seed planes
    cw_v_t,    # int32 [n, 128, Kw]   packed CW value planes
    cw_tl,     # int32 [n, Kw]        packed tl bits
    cw_tr,     # int32 [n, Kw]        packed tr bits
    cw_np1_t,  # int32 [128, Kw]      packed final CW planes
    x_mask,    # int32 [n, M, 1]      walk-order input-bit masks
    *,
    b: int,
    m_tile: int = 8,
    kw_tile: int = 128,
    level_chunk: int = 8,
    interpret: bool = False,
):
    """Party ``b`` many-keys eval; returns y planes int32 [128, M, Kw]."""
    n, _, kw = cw_s_t.shape
    m = x_mask.shape[1]
    m_tile = min(m_tile, m)
    kw_tile = min(kw_tile, kw)
    lc = min(level_chunk, n)
    if m % m_tile or kw % kw_tile or n % lc:
        raise ShapeError(
            f"shape ({n} levels, {m} points, {kw} key words) not divisible "
            f"by tiling ({lc}, {m_tile}, {kw_tile})")

    s = jnp.broadcast_to(s0_t[:, None, :], (128, m, kw))
    t = jnp.full((m, kw), jnp.int32(-1 if b else 0), jnp.int32)
    v = jnp.zeros((128, m, kw), jnp.int32)

    grid = (kw // kw_tile, m // m_tile)
    state_spec = pl.BlockSpec((128, m_tile, kw_tile), lambda k, j: (0, j, k))
    t_spec = pl.BlockSpec((m_tile, kw_tile), lambda k, j: (j, k))
    call = pl.pallas_call(
        partial(_kernel, lc=lc, interpret=interpret),
        out_shape=(
            jax.ShapeDtypeStruct((128, m, kw), jnp.int32),
            jax.ShapeDtypeStruct((m, kw), jnp.int32),
            jax.ShapeDtypeStruct((128, m, kw), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 1), lambda k, j: (0, 0, 0)),
            state_spec, t_spec, state_spec,
            pl.BlockSpec((lc, 128, kw_tile), lambda k, j: (0, 0, k)),
            pl.BlockSpec((lc, 128, kw_tile), lambda k, j: (0, 0, k)),
            pl.BlockSpec((lc, kw_tile), lambda k, j: (0, k)),
            pl.BlockSpec((lc, kw_tile), lambda k, j: (0, k)),
            pl.BlockSpec((lc, m_tile, 1), lambda k, j: (0, j, 0)),
        ],
        out_specs=(state_spec, t_spec, state_spec),
        interpret=interpret,
    )
    for c0 in range(0, n, lc):
        s, t, v = call(
            rk, s, t, v,
            jax.lax.dynamic_slice_in_dim(cw_s_t, c0, lc, 0),
            jax.lax.dynamic_slice_in_dim(cw_v_t, c0, lc, 0),
            jax.lax.dynamic_slice_in_dim(cw_tl, c0, lc, 0),
            jax.lax.dynamic_slice_in_dim(cw_tr, c0, lc, 0),
            jax.lax.dynamic_slice_in_dim(x_mask, c0, lc, 0),
        )
    return v ^ s ^ (cw_np1_t[:, None, :] & t[None, :, :])
