"""Pallas TPU kernel for the full DCF evaluation walk.

The XLA bitsliced path (backends.jax_bitsliced) is HBM-bound: every level of
the 8N-bit GGM walk materializes multi-MB plane intermediates between fused
ops, so the chip streams ~TBs per batch.  This kernel keeps the ENTIRE
walk — the bitsliced AES-256 Hirose PRG, correction-word application, and
the left/right mux (reference semantics: /root/reference/src/lib.rs:163-204,
/root/reference/src/prg.rs:42-73) — in VMEM.

Layouts (lam = 16 only — one AES block per seed, one Hirose cipher; larger
lam falls back to the XLA path):

    planes   int32, bit-major order p' = bit*16 + byte
             (utils.bits.bitmajor_perm) so S-box inputs are contiguous
             16-row sublane slices
    lanes    points packed 32-per-word; a grid step owns WT words
             (32*WT points)
    grid     (K, W // WT): keys x point tiles.  The n-level walk runs as a
             fori_loop INSIDE the kernel with the (s, t, v) carry live in
             vregs/VMEM — one grid step per point tile, not per level, so
             there is no per-level grid/DMA overhead (the per-level variant
             measured ~44us/step of overhead vs ~9us of compute).  All n
             correction words for the key ride in the step's VMEM block
             (n=128: 2 x 64 KB) and are indexed dynamically by the loop.

Everything is int32 (identical bit patterns to uint32 for XOR/AND/OR; SMEM
scalars want int32).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops._compat import CompilerParams as _CompilerParams

from dcf_tpu.ops.aes_bitsliced import (
    aes256_encrypt_planes_bitmajor,
    aes_walk_cipher_v3,
    prep_rk_bitmajor_v3,
)
from dcf_tpu.ops.group_accum import (group_width, planes_add_bitmajor16,
                                     planes_neg_bitmajor16)

__all__ = ["dcf_eval_pallas", "DEFAULT_TILE_WORDS", "make_aes", "walk_levels"]

# 4096 points per grid step.  128 is the Mosaic lane-granule minimum and
# measured fastest on v5e with the v3 cipher (124 ms vs 195/215 ms for
# 256/512 at 2^20 points): smaller tiles mean fewer vregs per gate op in the
# 113-gate S-box chain, which schedules better, and a smaller VMEM live set.
# See benchmarks/ROOFLINE.md for the full attribution.
DEFAULT_TILE_WORDS = 128


def make_aes(rk, interpret: bool):
    """The per-grid-step AES closure: the conjugated-ShiftRows cipher (v3)
    lowers ~2.5x faster under Mosaic but its unrolled slice-concat graph
    makes the CPU interpreter crawl; the two are bit-identical
    (tests/test_bitsliced.py), so interpret mode keeps the compact v1
    graph."""
    ones = jnp.int32(-1)
    if interpret:
        def aes(state):
            return aes256_encrypt_planes_bitmajor(jnp, rk, state, ones)
        return aes
    rk_p = prep_rk_bitmajor_v3(jnp, rk)  # hoisted: once per grid step

    def aes(state):
        return aes_walk_cipher_v3(jnp, rk_p, state, ones)
    return aes


def walk_levels(aes, lbm, s0, t0, v0, cw_s_ref, cw_v_ref, cw_t_ref, xm_ref,
                n: int, group: str = "xor"):
    """The n-level GGM walk loop on packed planes, shared by the from-root
    kernel below and the prefix-shared kernel (ops.pallas_prefix).  The
    cw/xm refs are indexed [0, i] per level i in 0..n-1.

    ``group`` selects the value accumulation: XOR plane algebra, or the
    additive group's per-lane mod-2^w add over the bit-major planes
    (ops.group_accum.planes_add_bitmajor16 — static slice/concat only, so
    it lowers in Mosaic).  The party sign of additive shares is applied
    by the caller at the walk exit, not per level.
    """
    ones = jnp.int32(-1)
    gw = group_width(group)  # 0 for xor
    wt = s0.shape[1]

    def level(i, carry):
        s, t, v = carry
        sp = s ^ ones
        # One Hirose PRG call = AES-256 over (seed, seed^c) side by side.
        enc = aes(jnp.concatenate([s, sp], axis=1))
        sl_raw = enc[:, :wt] ^ s   # left child seed planes (pre-mask)
        vl_raw = enc[:, wt:] ^ sp  # left child value planes (pre-mask)
        # t bits come from the pre-mask planes (src/prg.rs:63-64); the right
        # half is the never-encrypted Miyaguchi copy: s_r = seed, v_r = seed^c.
        t_l = sl_raw[0:1, :]
        t_r = vl_raw[0:1, :]
        s_l = sl_raw & lbm
        v_l = vl_raw & lbm
        s_r = s & lbm
        v_r = sp & lbm

        cs = cw_s_ref[0, i]  # [128, 1]
        cv = cw_v_ref[0, i]
        ctl = cw_t_ref[0, i, 0]
        ctr = cw_t_ref[0, i, 1]
        gate = t  # [1, wt], broadcasts over planes
        csg = cs & gate  # materialized once: both children consume it
        s_l = s_l ^ csg
        s_r = s_r ^ csg
        t_l = t_l ^ (t & ctl)
        t_r = t_r ^ (t & ctr)

        xm = xm_ref[0, i]  # [1, wt] input-bit lane masks for this level
        nxm = xm ^ ones
        if gw:
            v_hat = (v_r & xm) | (v_l & nxm)
            v = planes_add_bitmajor16(
                v, planes_add_bitmajor16(v_hat, cv & gate, gw), gw)
        else:
            v = v ^ (v_r & xm) ^ (v_l & nxm) ^ (cv & gate)
        s = (s_r & xm) | (s_l & nxm)
        t = (t_r & xm) | (t_l & nxm)
        return (s, t, v)

    return jax.lax.fori_loop(0, n, level, (s0, t0, v0))


def _kernel(rk_ref, s0_ref, cw_s_ref, cw_v_ref, cw_np1_ref, cw_t_ref, xm_ref,
            y_ref, *, b: int, n: int, interpret: bool, group: str = "xor"):
    wt = xm_ref.shape[3]
    ones = jnp.int32(-1)
    gw = group_width(group)
    aes = make_aes(rk_ref[:], interpret)

    # PRG mask: output bit 8*lam-1 is cleared (reference src/prg.rs:65-68);
    # for lam=16 that is byte 15 bit 0 -> bit-major plane 15.
    plane_idx = jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0)
    lbm = jnp.where(plane_idx == 15, jnp.int32(0), ones)

    # (broadcast via ^0: jnp.broadcast_to doesn't lower in Mosaic)
    s0 = s0_ref[0] ^ jnp.zeros((128, wt), jnp.int32)
    t0 = jnp.full((1, wt), ones if b else jnp.int32(0), jnp.int32)
    v0 = jnp.zeros((128, wt), jnp.int32)

    s, t, v = walk_levels(aes, lbm, s0, t0, v0, cw_s_ref, cw_v_ref,
                          cw_t_ref, xm_ref, n, group)
    if not gw:
        y_ref[0] = v ^ s ^ (cw_np1_ref[0] & t)
        return
    y = planes_add_bitmajor16(
        v, planes_add_bitmajor16(s, cw_np1_ref[0] & t, gw), gw)
    # Signed-share contract: party 1 negates once at the walk exit.
    y_ref[0] = planes_neg_bitmajor16(y, gw) if b else y


def dcf_eval_pallas(
    rk,        # int32 [15, 128, 1]    bit-major round-key masks (one cipher)
    s0_t,      # int32 [K, 128, 1]     party seed planes
    cw_s_t,    # int32 [K, n, 128, 1]  CW seed planes, one block per level
    cw_v_t,    # int32 [K, n, 128, 1]  CW value planes
    cw_np1_t,  # int32 [K, 128, 1]     final CW planes
    cw_t,      # int32 [K, n, 2]       (tl, tr) as 0/-1 scalars
    x_mask,    # int32 [Kx, n, 1, W]   per-level input-bit lane masks
    *,
    b: int,
    tile_words: int = DEFAULT_TILE_WORDS,
    interpret: bool = False,
    group: str = "xor",
):
    """Party ``b`` DCF eval; returns y planes int32 [K, 128, W] (bit-major).

    Additive ``group`` planes come out as SIGNED shares (party 1 negated
    in-kernel); reconstruction is a plain per-lane add after the
    plane->byte conversion.
    """
    k_num = s0_t.shape[0]
    n = cw_s_t.shape[1]
    kx, _, _, w = x_mask.shape
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"point words {w} not a multiple of tile {wt}")
    shared = kx == 1

    grid = (k_num, w // wt)
    # The flagship K=1 shape sits exactly at the 16 MB scoped-vmem
    # default; a multi-key grid's extra block buffering tips it over by
    # ~256 KB (measured at K=8, n=128, wt=128), so the limit is raised
    # explicitly — same remedy as the narrow kernel.
    return pl.pallas_call(
        partial(_kernel, b=b, n=n, interpret=interpret, group=group),
        out_shape=jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
        grid=grid,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
        in_specs=[
            pl.BlockSpec((15, 128, 1), lambda k, j: (0, 0, 0)),
            pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0)),
            pl.BlockSpec((1, n, 128, 1), lambda k, j: (k, 0, 0, 0)),
            pl.BlockSpec((1, n, 128, 1), lambda k, j: (k, 0, 0, 0)),
            pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0)),
            pl.BlockSpec((1, n, 2), lambda k, j: (k, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n, 1, wt),
                         (lambda k, j: (0, 0, 0, j)) if shared
                         else (lambda k, j: (k, 0, 0, j))),
        ],
        out_specs=pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j)),
        interpret=interpret,
    )(rk, s0_t, cw_s_t, cw_v_t, cw_np1_t, cw_t, x_mask)
