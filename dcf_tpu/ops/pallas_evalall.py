"""Pallas TPU kernel for full-domain DPF evaluation (EvalAll, lam=32).

``ops.pallas_tree`` expands the lam=16 DCF tree breadth-first; this is
its DPF twin at the device DPF width (lam=32, two AES blocks — the
``narrow_prg_expand`` shape every narrow kernel shares), generalized
from "cache the top k levels" (the PR 3/7 frontier build) to "emit
every leaf": per-point full-domain evaluation costs n * 2^n PRG calls,
the level-order expansion costs sum_i 2^i ≈ 2^{n+1} — the classic FSS
EvalAll optimization, and the engine of 2-server PIR (every query
touches the whole database, so the per-leaf cost IS the query cost).

One kernel application = one (key, tile) of one level: a tile of parent
nodes (packed 32 per uint32 lane word, bit-major planes) expands into
left/right child tiles with the seed correction applied; there is no
value accumulator — the DPF key has no ``cw_v`` (protocols.dpf).  The
batch grid is (K, words/tile): K-packed like the keygen kernel, nodes
in lanes like the eval kernels.

Children per Hirose at lam=32 (blocks 0/1 = bytes 0..15 / 16..31):

    s_l = (E0(s0)^s0, s1)    s_r = (s0, E17(s1)^s1)    (src/prg.rs:48-62)

with the global 8*lam-1 mask bit falling INSIDE block 1 (bit-major
plane 15), so block-1 child quantities mask with ``lbm`` and block 0 is
never masked.  t-bits are the pre-mask plane 0 of the two half-0
buffers, exactly what ``narrow_prg_expand`` returns.

Levels double the node arrays as [all-left ; all-right], so leaf array
position p holds domain point bitreverse_n(p) — consumers account for
it arithmetically (``backends.evalall``).  The top of the tree
(< 2^k0 nodes) is host-expanded; the device runs levels k0..n-1.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dcf_tpu.ops._compat import CompilerParams as _CompilerParams
from dcf_tpu.ops.pallas_narrow import make_narrow_aes, narrow_prg_expand

__all__ = ["dpf_tree_expand_device", "dpf_tree_expand_raw"]


def _expand_kernel(rk2_ref, cs0_ref, cs1_ref, ct_ref,
                   s0_ref, s1_ref, t_ref,
                   sl0_o, sl1_o, tl_o, sr0_o, sr1_o, tr_o,
                   *, interpret: bool):
    ones = jnp.int32(-1)
    wt = t_ref.shape[2]
    aes = make_narrow_aes(rk2_ref, wt, interpret)
    lbm = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0) == 15,
        jnp.int32(0), ones)

    s0 = s0_ref[0]
    s1 = s1_ref[0]
    t = t_ref[0]  # [1, wt]
    e_s0, _e_v0, e_s1, _e_v1, _sp0, _sp1, t_l, t_r = \
        narrow_prg_expand(aes, s0, s1)
    cs0g = cs0_ref[0] & t
    cs1g = cs1_ref[0] & t
    sl0_o[0] = e_s0 ^ cs0g
    sl1_o[0] = (s1 & lbm) ^ cs1g
    sr0_o[0] = s0 ^ cs0g
    sr1_o[0] = (e_s1 & lbm) ^ cs1g
    tl_o[0] = t_l ^ (t & ct_ref[0, 0])
    tr_o[0] = t_r ^ (t & ct_ref[0, 1])


def _expand_level(rk2, cs0, cs1, ct, s0, s1, t, *, interpret: bool):
    """One tree level for K packed keys: [K, 128, W] parents -> six
    [K, .., W] child halves."""
    k_num, _, w = s0.shape
    wt = min(128, w)
    grid = (k_num, w // wt)
    state_spec = pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j))
    t_spec = pl.BlockSpec((1, 1, wt), lambda k, j: (k, 0, j))
    cw_spec = pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0))
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_expand_kernel, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda k, j: (0, 0, 0)),
            cw_spec, cw_spec,
            pl.BlockSpec((1, 2), lambda k, j: (k, 0),
                         memory_space=pltpu.SMEM),
            state_spec, state_spec, t_spec,
        ],
        out_specs=(state_spec, state_spec, t_spec,
                   state_spec, state_spec, t_spec),
        interpret=interpret,
    )(rk2, cs0, cs1, ct, s0, s1, t)


@partial(jax.jit, static_argnames=("k0", "k1", "interpret"))
def dpf_tree_expand_raw(rk2, cs0_t, cs1_t, ct_pm, s0, s1, t,
                        k0: int, k1: int, interpret: bool = False):
    """Expand levels k0..k1-1 WITHOUT finalizing: returns the raw
    (s0, s1, t) node planes at level k1 (int32 [K, 128, 2^k1 / 32] x2 +
    [K, 1, 2^k1 / 32]), leaf order bitreverse_k1 per key.

    rk2 int32 [15, 128, 2]; cs0_t/cs1_t int32 [K, n, 128, 1] bit-major
    seed-CW plane masks (blocks 0/1); ct_pm int32 [K, n, 2] (0/-1);
    s0/s1/t the level-k0 frontier planes.
    """
    for i in range(k0, k1):
        sl0, sl1, tl, sr0, sr1, tr = _expand_level(
            rk2, cs0_t[:, i], cs1_t[:, i], ct_pm[:, i], s0, s1, t,
            interpret=interpret)
        s0 = jnp.concatenate([sl0, sr0], axis=2)
        s1 = jnp.concatenate([sl1, sr1], axis=2)
        t = jnp.concatenate([tl, tr], axis=2)
    return s0, s1, t


@partial(jax.jit, static_argnames=("k0", "n", "interpret"))
def dpf_tree_expand_device(rk2, cs0_t, cs1_t, ct_pm, np10_t, np11_t,
                           s0, s1, t, k0: int, n: int,
                           interpret: bool = False):
    """Expand levels k0..n-1 and finalize leaves.

    np10_t/np11_t int32 [K, 128, 1]: the leaf-CW plane masks (blocks
    0/1).  Returns ``(y0, y1, t)``: the two 16-byte BLOCKS of the leaf
    shares as int32 planes [K, 128, 2^n / 32] plus the leaf t-bit lane
    words [K, 1, 2^n / 32], all in bitreverse_n order.  The t planes
    are the PIR selection-vector share: t0 ^ t1 is 1 exactly at
    bitreverse_n(alpha) (workloads.py consumes them directly — the
    leaf-share planes are only needed when the DPF payload beta itself
    matters).
    """
    s0, s1, t = dpf_tree_expand_raw(rk2, cs0_t, cs1_t, ct_pm, s0, s1, t,
                                    k0=k0, k1=n, interpret=interpret)
    return s0 ^ (np10_t & t), s1 ^ (np11_t & t), t
