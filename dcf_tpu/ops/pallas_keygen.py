"""K-packed Pallas kernel for on-device GGM keygen (lam >= 48).

Keygen is the same narrow walk as evaluation, run once per KEY instead
of once per point: per level both parties' seeds expand through the
identical Hirose PRG, the lose-side correction words derive from the
XOR of the two expansions, and the keep-side children advance the walk
(reference src/lib.rs:86-161; ``gen.gen_batch`` is the host twin this
kernel is pinned byte-identical to).  The kernel therefore consumes the
SAME per-level AES core as the eval kernels — ``make_narrow_aes`` +
``narrow_prg_expand`` from ``ops.pallas_narrow`` — so gen and eval
cannot drift apart at the cipher layer: one walk implementation, one
parity surface.

Layout: the batch axis is KEYS, packed 32-per-int32-lane-word
(W = ceil(K/32) words), with each 16-byte block a separate [128, W]
bit-major plane tile — the eval kernels' exact convention with points
swapped for keys.  Per level the kernel runs TWO cipher applications
(one per party, each covering that party's four encryptions) and emits
the correction-word planes level by level in the hybrid evaluator's
staged two-block decomposition: ``cs0``/``cs1``/``cv0``/``cv1``
[n, 128, W], ``cw_tl``/``cw_tr`` [n, 1, W], the final-CW blocks
``np1_0``/``np1_1`` [128, W], plus both parties' control-bit
TRAJECTORIES [n, 1, W] (t at entry of each level) — the wide tail's
only coupling to the walk.

The wide part (bytes 32..lam-1) is keygen's mirror of the eval-side
affine split (``backends.large_lambda``): beyond byte 32 the Hirose PRG
is a structural copy, so per level

    s_cw[wide]  = mask(s_a ^ s_b)          (lose side == keep side)
    v_cw[wide]  = mask(s_a ^ s_b) ^ v_alpha ^ beta * gate
    v_alpha'    = beta * gate              (v_l == v_r: the walk
                                            accumulation cancels)
    s_p'[wide]  = mask(s_p) ^ s_cw * t_p   (p in {a, b})

— a pure GF(2) recursion in the per-level alpha bits and the two
trajectories, computed as one ``lax.scan`` over uint8 planes
(``_keygen_wide_tail``).  ``mask`` clears the global 8*lam-1 bit, which
always lies in the wide slice for lam > 32 (src/prg.rs:65-68).

lam < 48 has no wide/narrow split and is served by the keys-in-lanes
device generator (``backends.device_gen``); ``gen.gen_on_device`` is
the one router.  Bit-exact parity with the host ``gen_batch`` across
(lam, K, bound) is pinned by tests/test_keygen_device.py.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops._compat import CompilerParams as _CompilerParams
from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor
from dcf_tpu.ops.pallas_narrow import make_narrow_aes, narrow_prg_expand
from dcf_tpu.spec import Bound, hirose_used_cipher_indices
from dcf_tpu.utils.bits import (
    bitmajor_perm,
    bits_lsb_to_bytes,
    byte_bits_lsb,
    byte_bits_msb,
    pack_lanes,
    unpack_lanes,
)

__all__ = ["PallasDpfKeyGen", "PallasKeyGen", "dcf_keygen_walk_pallas",
           "dpf_keygen_walk_pallas"]

NARROW = 32  # bytes covered by the encrypted blocks (ciphers 0, 17)


def _kernel(rk2_ref, s0a0_ref, s0a1_ref, s0b0_ref, s0b1_ref, beta0_ref,
            beta1_ref, am_ref,
            cs0_ref, cs1_ref, cv0_ref, cv1_ref, tl_ref, tr_ref,
            np10_ref, np11_ref, tra_ref, trb_ref,
            *, n: int, lt_beta: bool, interpret: bool):
    wt = am_ref.shape[2]
    ones = jnp.int32(-1)
    aes = make_narrow_aes(rk2_ref, wt, interpret)

    def mux(m, if_one, if_zero):
        return (if_one & m) | (if_zero & (m ^ ones))

    z = jnp.zeros((128, wt), jnp.int32)
    sa0 = s0a0_ref[...] ^ z  # party 0 seed planes, blocks 0/1
    sa1 = s0a1_ref[...] ^ z
    sb0 = s0b0_ref[...] ^ z  # party 1
    sb1 = s0b1_ref[...] ^ z
    beta0 = beta0_ref[...] ^ z
    beta1 = beta1_ref[...] ^ z
    t_a = jnp.zeros((1, wt), jnp.int32)       # t^(0)_0 = 0
    t_b = jnp.full((1, wt), ones, jnp.int32)  # t^(0)_1 = 1
    va0 = z  # v_alpha blocks
    va1 = z

    def level(i, carry):
        sa0, sa1, sb0, sb1, t_a, t_b, va0, va1 = carry
        tra_ref[pl.dslice(i, 1)] = t_a[None]  # t at level entry
        trb_ref[pl.dslice(i, 1)] = t_b[None]
        am = am_ref[i]  # [1, wt]: -1 where the walk bit of alpha is 1
        # Both parties through the ONE shared per-level PRG core — the
        # same two cipher applications gen_batch's two prg.gen calls do.
        ea_s0, ea_v0, ea_s1, ea_v1, spa0, spa1, tla, tra = \
            narrow_prg_expand(aes, sa0, sa1)
        eb_s0, eb_v0, eb_s1, eb_v1, spb0, spb1, tlb, trb = \
            narrow_prg_expand(aes, sb0, sb1)
        # lose side: L when the alpha bit is 1, R when 0
        # (src/lib.rs:107-111); child blocks per narrow_prg_expand.
        s_cw0 = mux(am, ea_s0 ^ eb_s0, sa0 ^ sb0)
        s_cw1 = mux(am, sa1 ^ sb1, ea_s1 ^ eb_s1)
        v_cw0 = mux(am, ea_v0 ^ eb_v0, spa0 ^ spb0) ^ va0
        v_cw1 = mux(am, spa1 ^ spb1, ea_v1 ^ eb_v1) ^ va1
        # beta folds into v_cw when the lose side matches the bound
        # (src/lib.rs:114-125): LT on lose==L (bit 1), GT on lose==R.
        bg = am if lt_beta else am ^ ones
        v_cw0 = v_cw0 ^ (beta0 & bg)
        v_cw1 = v_cw1 ^ (beta1 & bg)
        # keep-side v accumulation (gen.gen_batch's v_alpha update)
        va0 = va0 ^ mux(am, spa0 ^ spb0, ea_v0 ^ eb_v0) ^ v_cw0
        va1 = va1 ^ mux(am, ea_v1 ^ eb_v1, spa1 ^ spb1) ^ v_cw1
        tl_cw = tla ^ tlb ^ am ^ ones
        tr_cw = tra ^ trb ^ am
        t_cw_keep = mux(am, tr_cw, tl_cw)
        # keep-side children + CW correction gated by each party's t
        new_sa0 = mux(am, sa0, ea_s0) ^ (s_cw0 & t_a)
        new_sa1 = mux(am, ea_s1, sa1) ^ (s_cw1 & t_a)
        new_sb0 = mux(am, sb0, eb_s0) ^ (s_cw0 & t_b)
        new_sb1 = mux(am, eb_s1, sb1) ^ (s_cw1 & t_b)
        new_t_a = mux(am, tra, tla) ^ (t_a & t_cw_keep)
        new_t_b = mux(am, trb, tlb) ^ (t_b & t_cw_keep)
        cs0_ref[pl.dslice(i, 1)] = s_cw0[None]
        cs1_ref[pl.dslice(i, 1)] = s_cw1[None]
        cv0_ref[pl.dslice(i, 1)] = v_cw0[None]
        cv1_ref[pl.dslice(i, 1)] = v_cw1[None]
        tl_ref[pl.dslice(i, 1)] = tl_cw[None]
        tr_ref[pl.dslice(i, 1)] = tr_cw[None]
        return (new_sa0, new_sa1, new_sb0, new_sb1, new_t_a, new_t_b,
                va0, va1)

    sa0, sa1, sb0, sb1, _t_a, _t_b, va0, va1 = jax.lax.fori_loop(
        0, n, level, (sa0, sa1, sb0, sb1, t_a, t_b, va0, va1))
    np10_ref[...] = sa0 ^ sb0 ^ va0  # cw_{n+1}, narrow blocks
    np11_ref[...] = sa1 ^ sb1 ^ va1


def dcf_keygen_walk_pallas(
    rk2,        # int32 [15, 128, 2]  bit-major round keys (ciphers 0, 17)
    s0a0, s0a1,  # int32 [128, W]     party-0 seed planes, blocks 0/1
    s0b0, s0b1,  # int32 [128, W]     party-1 seed planes
    beta0, beta1,  # int32 [128, W]   beta planes, blocks 0/1
    alpha_mask,  # int32 [n, 1, W]    per-level walk-order alpha-bit masks
    *,
    lt_beta: bool,
    tile_words: int = 128,
    interpret: bool = False,
):
    """The full n-level keygen walk for W*32 lane-packed keys.

    Returns ``(cs0, cs1, cv0, cv1 [n, 128, W], cw_tl, cw_tr [n, 1, W],
    np1_0, np1_1 [128, W], tr_a, tr_b [n, 1, W])`` — the narrow
    correction-word planes in the hybrid evaluator's staged two-block
    decomposition plus both parties' level-entry control-bit
    trajectories (the wide tail's input)."""
    n = alpha_mask.shape[0]
    w = alpha_mask.shape[2]
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"key words {w} not a multiple of tile {wt}")

    grid = (w // wt,)
    plane = pl.BlockSpec((128, wt), lambda j: (0, j))
    level_out = pl.BlockSpec((n, 128, wt), lambda j: (0, 0, j))
    bit_out = pl.BlockSpec((n, 1, wt), lambda j: (0, 0, j))
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_kernel, n=n, lt_beta=lt_beta, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda j: (0, 0, 0)),
            plane, plane, plane, plane, plane, plane,
            pl.BlockSpec((n, 1, wt), lambda j: (0, 0, j)),
        ],
        out_specs=(
            level_out, level_out, level_out, level_out,
            bit_out, bit_out, plane, plane, bit_out, bit_out,
        ),
        interpret=interpret,
    )(rk2, s0a0, s0a1, s0b0, s0b1, beta0, beta1, alpha_mask)


@partial(jax.jit, static_argnames=("lam", "lt_beta", "k_num"))
def _keygen_wide_tail(s0w, beta_w, abits, tr_a, tr_b, *, lam: int,
                      lt_beta: bool, k_num: int):
    """The wide (bytes 32..lam-1) correction words from the narrow
    trajectories — keygen's mirror of the eval-side affine split
    (module docstring).  ``s0w`` uint8 [K, 2, lam-32], ``beta_w`` uint8
    [K, lam-32], ``abits`` uint8 [K, n] walk-order alpha bits,
    ``tr_a``/``tr_b`` int32 [n, 1, W] kernel trajectory planes
    (W*32 >= K = k_num).  Returns (cw_s_w, cw_v_w uint8 [K, n, lam-32],
    np1_w uint8 [K, lam-32])."""
    n = abits.shape[1]
    wd = lam - NARROW

    def lane_bits(tr):  # [n, 1, W] lane planes -> uint8 [n, K]
        u = jax.lax.bitcast_convert_type(tr, jnp.uint32)
        b = (u[..., None] >> jnp.arange(32, dtype=jnp.uint32)) \
            & jnp.uint32(1)
        return b.reshape(n, -1)[:, :k_num].astype(jnp.uint8)

    t_a = lane_bits(tr_a)
    t_b = lane_bits(tr_b)
    # mask: clear the global 8*lam-1 bit (byte lam-1, wide index lam-33)
    mask_vec = jnp.full((wd,), 255, jnp.uint8).at[lam - 33].set(
        jnp.uint8(0xFE))

    def body(carry, lev):
        s_a, s_b, v = carry
        a_bit, ta, tb = lev  # uint8 [K] each
        bg = (a_bit if lt_beta else a_bit ^ jnp.uint8(1))[:, None]
        sx = (s_a ^ s_b) & mask_vec     # s_cw (lose == keep wide)
        v_cw = sx ^ v ^ beta_w * bg
        v2 = v ^ sx ^ v_cw              # keep-side v_l == v_r
        s_a2 = (s_a & mask_vec) ^ sx * ta[:, None]
        s_b2 = (s_b & mask_vec) ^ sx * tb[:, None]
        return (s_a2, s_b2, v2), (sx, v_cw)

    init = (s0w[:, 0], s0w[:, 1], jnp.zeros((k_num, wd), jnp.uint8))
    (s_a, s_b, v), (cw_s_w, cw_v_w) = jax.lax.scan(
        body, init, (abits.T, t_a, t_b))
    return (cw_s_w.transpose(1, 0, 2), cw_v_w.transpose(1, 0, 2),
            s_a ^ s_b ^ v)


@partial(jax.jit, static_argnames=("k_num",))
def _lanes_to_key_masks(planes, *, k_num: int):
    """Kernel lane planes [..., 128, W] -> per-key staged masks
    int32 [K, ..., 128, 1] (0 / -1) — the hybrid evaluator's
    ``put_bundle`` plane layout, derived on device so a generated image
    can stage without a host round-trip."""
    u = jax.lax.bitcast_convert_type(planes, jnp.uint32)
    bits = (u[..., None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)
    bits = bits.reshape(*planes.shape[:-1], -1)[..., :k_num]
    masks = jax.lax.bitcast_convert_type(
        bits * jnp.uint32(0xFFFFFFFF), jnp.int32)
    return jnp.moveaxis(masks, -1, 0)[..., None]


class PallasKeyGen:
    """On-device K-packed GGM keygen for the hybrid family (lam >= 48).

    Runs the narrow keygen walk as one Pallas kernel (keys in lanes) and
    the wide correction words as a GF(2) scan over the emitted
    trajectories; ``gen`` downloads and reassembles the standard host
    ``KeyBundle`` (byte-identical to ``gen.gen_batch`` — the DCFK wire
    bytes, the serve registration path and the durable store see exactly
    what the host keygen would have produced), while ``staged_planes``
    exposes the narrow image in the hybrid evaluator's staged layout
    without leaving the device.  Prefer the ``gen.gen_on_device`` router
    (or facade ``Dcf.gen(..., device=True)``) over direct construction.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 interpret: bool = False, tile_words: int = 128):
        if lam < 48 or lam % 16:
            # api-edge: constructor lam contract (mirrors the hybrid
            # evaluator; smaller lams route to backends.device_gen)
            raise ValueError(
                "PallasKeyGen wants lam >= 48 (a multiple of 16); "
                "use backends.device_gen for small lam")
        used = hirose_used_cipher_indices(lam, len(cipher_keys),
                                          warn=False)
        assert tuple(used) == (0, 17)
        self.lam = lam
        self.interpret = interpret
        self.tile_words = tile_words
        self.rk2 = jnp.asarray(np.concatenate(
            [round_key_masks_bitmajor(cipher_keys[i]) for i in used],
            axis=2))  # [15, 128, 2]
        self._perm = bitmajor_perm(16)
        self._inv_perm = np.argsort(self._perm)

    def _block_planes(self, a16: np.ndarray) -> jax.Array:
        """uint8 [K_pad, 16] -> bit-major keys-in-lanes planes
        int32 [128, W]."""
        bits = byte_bits_lsb(a16)[:, self._perm]  # [K, 128] bit-major
        return jnp.asarray(pack_lanes(
            np.ascontiguousarray(bits.T)).view(np.int32))

    def _walk(self, alphas, betas, s0s, bound: Bound):
        """Pad + stage + run the kernel.  Returns (out tuple, padded
        inputs).  Deliberately NOT memoized: a memo would retain seeds
        and correction-word planes — key material — in a long-lived
        generator object; callers who need the bundle AND the staged
        planes from one walk use ``gen_with_planes``."""
        k = alphas.shape[0]
        k_pad = (k + 31) // 32 * 32
        if k_pad != k:
            pad = [(0, k_pad - k)]
            alphas = np.pad(alphas, pad + [(0, 0)])
            betas = np.pad(betas, pad + [(0, 0)])
            s0s = np.pad(s0s, pad + [(0, 0), (0, 0)])
        n = 8 * alphas.shape[1]
        am = pack_lanes(np.ascontiguousarray(
            byte_bits_msb(alphas).T)).view(np.int32)[:, None, :]
        out = dcf_keygen_walk_pallas(
            self.rk2,
            self._block_planes(s0s[:, 0, :16]),
            self._block_planes(s0s[:, 0, 16:32]),
            self._block_planes(s0s[:, 1, :16]),
            self._block_planes(s0s[:, 1, 16:32]),
            self._block_planes(betas[:, :16]),
            self._block_planes(betas[:, 16:32]),
            jnp.asarray(am),
            lt_beta=(bound is Bound.LT_BETA),
            tile_words=self.tile_words, interpret=self.interpret)
        return out, (alphas, betas, s0s, n, k_pad)

    def _check(self, alphas, betas, s0s) -> int:
        k = alphas.shape[0]
        if betas.shape != (k, self.lam) or s0s.shape != (k, 2, self.lam):
            raise ShapeError("alphas/betas/s0s shape mismatch")
        return k

    def gen(self, alphas: np.ndarray, betas: np.ndarray, s0s: np.ndarray,
            bound: Bound) -> KeyBundle:
        """Generate K keys on device: alphas uint8 [K, n_bytes], betas
        uint8 [K, lam], s0s uint8 [K, 2, lam].  Returns the two-party
        host ``KeyBundle``, byte-identical to ``gen_batch`` on the same
        inputs (K is padded to a lane-word multiple internally; pad keys
        are generated and discarded)."""
        k = self._check(alphas, betas, s0s)
        out, padded = self._walk(alphas, betas, s0s, bound)
        return self._assemble_bundle(out, padded, s0s, bound, k)

    def gen_with_planes(self, alphas: np.ndarray, betas: np.ndarray,
                        s0s: np.ndarray, bound: Bound, b: int):
        """ONE walk, both outputs: ``(host KeyBundle, party-b staged
        plane dict)`` — the no-round-trip registration flow (the bundle
        feeds the wire format / wide affine, the planes feed
        ``LargeLambdaBackend.put_bundle(bundle, dev_planes=...)``)
        without running the kernel twice and without retaining key
        material in a memo."""
        k = self._check(alphas, betas, s0s)
        out, padded = self._walk(alphas, betas, s0s, bound)
        return (self._assemble_bundle(out, padded, s0s, bound, k),
                self._assemble_planes(out, padded[2], k, b))

    def gen_with_planes_pair(self, alphas: np.ndarray, betas: np.ndarray,
                             s0s: np.ndarray, bound: Bound):
        """ONE walk, three outputs: ``(host KeyBundle, {0: planes,
        1: planes})`` — BOTH parties' staged plane dicts from a single
        kernel walk (ISSUE 11, the key-factory registration flow: the
        serving registry stages either party's image with zero host
        round-trip).  The correction-word planes are party-independent,
        so the two dicts share every array except the per-party seed
        planes — no duplicated device state, no second walk, and no
        key-material memo (same rule as ``gen_with_planes``)."""
        k = self._check(alphas, betas, s0s)
        out, padded = self._walk(alphas, betas, s0s, bound)
        s0s_p = padded[2]
        shared = self._shared_planes(out, k)
        planes = {b: dict(shared, **self._party_seed_planes(s0s_p, k, b))
                  for b in (0, 1)}
        return self._assemble_bundle(out, padded, s0s, bound, k), planes

    def _lane_blocks(self, b0, b1, k: int) -> np.ndarray:
        """Kernel lane planes [..., 128, W] x2 -> narrow key bytes
        uint8 [K, ..., 32] (shared by the DCF and DPF assemblers)."""
        by = [bits_lsb_to_bytes(
            np.moveaxis(unpack_lanes(np.asarray(
                jax.lax.bitcast_convert_type(a, jnp.uint32))),
                -1, 0)[:k][..., self._inv_perm])
            for a in (b0, b1)]
        return np.concatenate(by, axis=-1)

    def _lane_bits(self, a, k: int) -> np.ndarray:
        """Kernel t-bit planes [n, 1, W] -> uint8 [K, n]."""
        return np.moveaxis(
            unpack_lanes(np.asarray(
                jax.lax.bitcast_convert_type(a, jnp.uint32))),
            -1, 0)[:k, :, 0]

    def _assemble_bundle(self, out, padded, s0s, bound: Bound,
                         k: int) -> KeyBundle:
        cs0, cs1, cv0, cv1, tl, tr, np10, np11, tr_a, tr_b = out
        alphas_p, betas_p, s0s_p, n, _k_pad = padded
        cw_s_w, cw_v_w, np1_w = _keygen_wide_tail(
            jnp.asarray(s0s_p[:, :, NARROW:]),
            jnp.asarray(betas_p[:, NARROW:]),
            jnp.asarray(byte_bits_msb(alphas_p)),
            tr_a, tr_b, lam=self.lam,
            lt_beta=(bound is Bound.LT_BETA), k_num=alphas_p.shape[0])
        cw_s = np.concatenate(
            [self._lane_blocks(cs0, cs1, k), np.asarray(cw_s_w)[:k]],
            axis=-1)
        cw_v = np.concatenate(
            [self._lane_blocks(cv0, cv1, k), np.asarray(cw_v_w)[:k]],
            axis=-1)
        cw_np1 = np.concatenate(
            [self._lane_blocks(np10[None], np11[None], k)[:, 0],
             np.asarray(np1_w)[:k]], axis=-1)
        return KeyBundle(
            s0s=s0s.copy(),
            cw_s=cw_s, cw_v=cw_v,
            cw_t=np.stack(
                [self._lane_bits(tl, k), self._lane_bits(tr, k)], axis=2),
            cw_np1=cw_np1,
        )

    def staged_planes(self, alphas: np.ndarray, betas: np.ndarray,
                      s0s: np.ndarray, bound: Bound, b: int) -> dict:
        """Party ``b``'s NARROW key image in the hybrid evaluator's
        staged plane layout (``LargeLambdaBackend.put_bundle``'s
        ``_dev`` dict: per-key [K, n, 128, 1] CW masks, [K, 128, 1]
        seed/final planes, [K, n, 2] t-masks) — derived on device from
        the kernel's lane planes, no host round-trip.  Feed it to
        ``put_bundle(bundle, dev_planes=...)`` together with the host
        bundle (whose wide halves the affine tail still consumes) —
        and when you need both, ``gen_with_planes`` produces the pair
        from ONE walk."""
        k = self._check(alphas, betas, s0s)
        out, (_a, _b, s0s_p, _n, _k_pad) = self._walk(
            alphas, betas, s0s, bound)
        return self._assemble_planes(out, s0s_p, k, b)

    def _shared_planes(self, out, k: int) -> dict:
        """The party-INDEPENDENT half of the staged plane dict (the
        correction-word image is one image for both parties) — the one
        construction every planes producer shares, so the staged
        layout cannot silently fork between the single-party and
        pair paths."""
        cs0, cs1, cv0, cv1, tl, tr, np10, np11, _tr_a, _tr_b = out
        km = partial(_lanes_to_key_masks, k_num=k)
        # km on the [n, 1, W] t planes gives [K, n, 1, 1] masks each
        return dict(
            cs0=km(cs0), cs1=km(cs1), cv0=km(cv0), cv1=km(cv1),
            np1a=km(np10), np1b=km(np11),
            cw_t=jnp.concatenate(
                [km(tl), km(tr)], axis=2)[..., 0])  # [K, n, 2] 0/-1

    def _party_seed_planes(self, s0s_p, k: int, b: int) -> dict:
        km = partial(_lanes_to_key_masks, k_num=k)
        return dict(
            s0a=km(self._block_planes(s0s_p[:, b, :16])),
            s0b=km(self._block_planes(s0s_p[:, b, 16:32])))

    def _assemble_planes(self, out, s0s_p, k: int, b: int) -> dict:
        return dict(self._shared_planes(out, k),
                    **self._party_seed_planes(s0s_p, k, b))


# -- the DPF twin -------------------------------------------------------------


def _dpf_kernel(rk2_ref, s0a0_ref, s0a1_ref, s0b0_ref, s0b1_ref,
                beta0_ref, beta1_ref, am_ref,
                cs0_ref, cs1_ref, tl_ref, tr_ref, np10_ref, np11_ref,
                *, n: int, interpret: bool):
    """The DCF keygen walk minus the whole v column (protocols.dpf):
    same PRG core, same lose-side seed CW and keep-side t algebra, beta
    entering only through the leaf CW ``np1 = s_a ^ s_b ^ beta``.

    Unlike ``_kernel`` (hybrid, lam >= 48: the global masked byte is
    wide), lam == NARROW puts the Hirose 8*lam-1 mask bit INSIDE block 1
    — bit-major plane 15 (bit 0 of byte 15) — so every block-1 seed
    quantity masks with ``lbm`` exactly where the host PRG masks its
    outputs (src/prg.rs:65-68).  Block 0 is never masked."""
    wt = am_ref.shape[2]
    ones = jnp.int32(-1)
    aes = make_narrow_aes(rk2_ref, wt, interpret)
    lbm = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0) == 15,
        jnp.int32(0), ones)

    def mux(m, if_one, if_zero):
        return (if_one & m) | (if_zero & (m ^ ones))

    z = jnp.zeros((128, wt), jnp.int32)
    sa0 = s0a0_ref[...] ^ z  # party 0 seed planes, blocks 0/1
    sa1 = s0a1_ref[...] ^ z
    sb0 = s0b0_ref[...] ^ z  # party 1
    sb1 = s0b1_ref[...] ^ z
    t_a = jnp.zeros((1, wt), jnp.int32)       # t^(0)_0 = 0
    t_b = jnp.full((1, wt), ones, jnp.int32)  # t^(0)_1 = 1

    def level(i, carry):
        sa0, sa1, sb0, sb1, t_a, t_b = carry
        am = am_ref[i]  # [1, wt]: -1 where the walk bit of alpha is 1
        ea_s0, _ea_v0, ea_s1, _ea_v1, _spa0, _spa1, tla, tra = \
            narrow_prg_expand(aes, sa0, sa1)
        eb_s0, _eb_v0, eb_s1, _eb_v1, _spb0, _spb1, tlb, trb = \
            narrow_prg_expand(aes, sb0, sb1)
        # lose side: L when the alpha bit is 1, R when 0
        s_cw0 = mux(am, ea_s0 ^ eb_s0, sa0 ^ sb0)
        s_cw1 = mux(am, sa1 ^ sb1, ea_s1 ^ eb_s1) & lbm
        tl_cw = tla ^ tlb ^ am ^ ones
        tr_cw = tra ^ trb ^ am
        t_cw_keep = mux(am, tr_cw, tl_cw)
        new_sa0 = mux(am, sa0, ea_s0) ^ (s_cw0 & t_a)
        new_sa1 = (mux(am, ea_s1, sa1) & lbm) ^ (s_cw1 & t_a)
        new_sb0 = mux(am, sb0, eb_s0) ^ (s_cw0 & t_b)
        new_sb1 = (mux(am, eb_s1, sb1) & lbm) ^ (s_cw1 & t_b)
        new_t_a = mux(am, tra, tla) ^ (t_a & t_cw_keep)
        new_t_b = mux(am, trb, tlb) ^ (t_b & t_cw_keep)
        cs0_ref[pl.dslice(i, 1)] = s_cw0[None]
        cs1_ref[pl.dslice(i, 1)] = s_cw1[None]
        tl_ref[pl.dslice(i, 1)] = tl_cw[None]
        tr_ref[pl.dslice(i, 1)] = tr_cw[None]
        return (new_sa0, new_sa1, new_sb0, new_sb1, new_t_a, new_t_b)

    sa0, sa1, sb0, sb1, _t_a, _t_b = jax.lax.fori_loop(
        0, n, level, (sa0, sa1, sb0, sb1, t_a, t_b))
    np10_ref[...] = sa0 ^ sb0 ^ beta0_ref[...]  # cw_{n+1} = s_a^s_b^beta
    np11_ref[...] = sa1 ^ sb1 ^ beta1_ref[...]


def dpf_keygen_walk_pallas(
    rk2,        # int32 [15, 128, 2]  bit-major round keys (ciphers 0, 17)
    s0a0, s0a1,  # int32 [128, W]     party-0 seed planes, blocks 0/1
    s0b0, s0b1,  # int32 [128, W]     party-1 seed planes
    beta0, beta1,  # int32 [128, W]   beta planes, blocks 0/1
    alpha_mask,  # int32 [n, 1, W]    per-level walk-order alpha-bit masks
    *,
    tile_words: int = 128,
    interpret: bool = False,
):
    """The full n-level DPF keygen walk for W*32 lane-packed keys.

    Returns ``(cs0, cs1 [n, 128, W], cw_tl, cw_tr [n, 1, W], np1_0,
    np1_1 [128, W])``.  lam == NARROW exactly — no wide tail, no
    trajectories: the two narrow blocks ARE the whole key."""
    n = alpha_mask.shape[0]
    w = alpha_mask.shape[2]
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"key words {w} not a multiple of tile {wt}")

    grid = (w // wt,)
    plane = pl.BlockSpec((128, wt), lambda j: (0, j))
    level_out = pl.BlockSpec((n, 128, wt), lambda j: (0, 0, j))
    bit_out = pl.BlockSpec((n, 1, wt), lambda j: (0, 0, j))
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_dpf_kernel, n=n, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((n, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
            jax.ShapeDtypeStruct((128, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda j: (0, 0, 0)),
            plane, plane, plane, plane, plane, plane,
            pl.BlockSpec((n, 1, wt), lambda j: (0, 0, j)),
        ],
        out_specs=(
            level_out, level_out, bit_out, bit_out, plane, plane,
        ),
        interpret=interpret,
    )(rk2, s0a0, s0a1, s0b0, s0b1, beta0, beta1, alpha_mask)


class PallasDpfKeyGen(PallasKeyGen):
    """On-device K-packed DPF keygen at lam == NARROW (= 32).

    The DPF key is two AES blocks wide — exactly ``narrow_prg_expand``'s
    shape — so the walk is one Pallas kernel with NO wide tail; the
    assembler reuses the DCF lane converters.  ``gen`` returns the host
    two-party ``DpfBundle``, byte-identical to
    ``protocols.dpf.dpf_gen_batch`` on the same inputs.  Prefer the
    ``protocols.dpf.dpf_gen_on_device`` router (fault seam + counted
    fallback) over direct construction.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 interpret: bool = False, tile_words: int = 128):
        if lam != NARROW:
            # api-edge: constructor lam contract (the device DPF width;
            # other lams take the host dpf_gen_batch walk)
            raise ValueError(
                f"PallasDpfKeyGen wants lam == {NARROW} (two narrow AES "
                f"blocks), got {lam}")
        used = hirose_used_cipher_indices(lam, len(cipher_keys),
                                          warn=False)
        assert tuple(used) == (0, 17)
        self.lam = lam
        self.interpret = interpret
        self.tile_words = tile_words
        self.rk2 = jnp.asarray(np.concatenate(
            [round_key_masks_bitmajor(cipher_keys[i]) for i in used],
            axis=2))  # [15, 128, 2]
        self._perm = bitmajor_perm(16)
        self._inv_perm = np.argsort(self._perm)

    def gen(self, alphas: np.ndarray, betas: np.ndarray,
            s0s: np.ndarray):
        """Generate K DPF keys on device: alphas uint8 [K, n_bytes],
        betas uint8 [K, 32], s0s uint8 [K, 2, 32].  Returns the
        two-party host ``DpfBundle`` (K padded to a lane-word multiple
        internally; pad keys are generated and discarded)."""
        from dcf_tpu.protocols.dpf import DpfBundle

        k = self._check(alphas, betas, s0s)
        k_pad = (k + 31) // 32 * 32
        s0s_p = s0s
        if k_pad != k:
            pad = [(0, k_pad - k)]
            alphas = np.pad(alphas, pad + [(0, 0)])
            betas = np.pad(betas, pad + [(0, 0)])
            s0s_p = np.pad(s0s, pad + [(0, 0), (0, 0)])
        am = pack_lanes(np.ascontiguousarray(
            byte_bits_msb(alphas).T)).view(np.int32)[:, None, :]
        cs0, cs1, tl, tr, np10, np11 = dpf_keygen_walk_pallas(
            self.rk2,
            self._block_planes(s0s_p[:, 0, :16]),
            self._block_planes(s0s_p[:, 0, 16:32]),
            self._block_planes(s0s_p[:, 1, :16]),
            self._block_planes(s0s_p[:, 1, 16:32]),
            self._block_planes(betas[:, :16]),
            self._block_planes(betas[:, 16:32]),
            jnp.asarray(am),
            tile_words=self.tile_words, interpret=self.interpret)
        return DpfBundle(
            s0s=s0s.copy(),
            cw_s=self._lane_blocks(cs0, cs1, k),
            cw_t=np.stack(
                [self._lane_bits(tl, k), self._lane_bits(tr, k)], axis=2),
            cw_np1=self._lane_blocks(np10[None], np11[None], k)[:, 0])
