"""Prefix-shared NARROW walk kernels for the large-lambda hybrid.

The hybrid evaluator (backends.large_lambda) reduces a lam-byte DCF
evaluation to a 32-byte two-block narrow walk plus a GF(2) affine wide
part.  That narrow walk is a from-root n-level walk — exactly the shape
the round-5 prefix-frontier machinery (ops.pallas_prefix) accelerates
for lam=16: a batch of shared points redundantly recomputes the top
k ~ log2(M) levels M times, while a 2^k-node frontier expanded ONCE per
(key, party) turns that into a per-point gather plus n-k walked levels.

This module is that machinery for the narrow walk.  Differences from the
lam=16 frontier (ops.pallas_prefix):

* the carry is FIVE pieces — (sa, sb, va, vb) block planes plus the
  t bit — so a frontier row is 16 int32 columns (sa|sb|va|vb, 4 each)
  instead of 8; the measured XLA gather is data-bound at 32 B
  (micro_gather.py: 64 B rows cost exactly 2x), so the 64 B row costs
  ~2x the lam=16 gather per point and the table cliff arrives one level
  earlier (2^21 rows = the same 128 MB);
* there is NO structurally-zero plane to stash t in (the narrow walk is
  unmasked — the big PRG's 8*lam-1 masked bit lives in the WIDE part,
  reference src/prg.rs:65-68), but the wide part needs the whole t-bit
  TRAJECTORY anyway, so the per-node trajectory prefix (gate bits
  0..k-1 plus the depth-k carry t at bit k, k+1 <= 32 bits) rides in a
  separate one-word-per-node table gathered with the same indices;
* the frontier is built ON DEVICE by walking all 2^k node prefixes k
  levels through the shared narrow level loop (``narrow_state_walk``),
  emitting raw carries instead of y — k*2^k PRG calls, vs the tree
  kernel's 2^{k+1}; still key material off the eval clock, and a narrow
  tree-expansion kernel remains the known upgrade if build cost ever
  matters (it has not: the build is one untimed pass per (key, party)).

The eval kernel gathers each point's row, repacks it with the in-kernel
32x32 butterfly bit transposes (ops.pallas_prefix.rows_to_state_planes,
~0.5 ms per table at M = 2^20), walks the remaining n-k levels via the
SAME level loop as the from-root narrow kernel, and emits the y blocks
plus the remaining trajectory — the wide matmul then consumes the
gathered top-k gate planes concatenated with the walked ones.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops._compat import CompilerParams as _CompilerParams

from dcf_tpu.ops.pallas_narrow import make_narrow_aes, narrow_walk_levels
from dcf_tpu.ops.pallas_prefix import rows_to_state_planes

__all__ = ["narrow_state_walk_pallas", "dcf_hybrid_prefix_pallas"]


def _state_kernel(rk2_ref, s0a_ref, s0b_ref, cs0_ref, cs1_ref, cv0_ref,
                  cv1_ref, cw_t_ref, xm_ref,
                  sa_ref, sb_ref, va_ref, vb_ref, tr_ref,
                  *, b: int, n: int, interpret: bool):
    wt = xm_ref.shape[3]
    ones = jnp.int32(-1)
    aes = make_narrow_aes(rk2_ref, wt, interpret)

    z = jnp.zeros((128, wt), jnp.int32)
    sa = s0a_ref[0] ^ z
    sb = s0b_ref[0] ^ z
    t = jnp.full((1, wt), ones if b else jnp.int32(0), jnp.int32)

    sa, sb, t, va, vb = narrow_walk_levels(
        aes, sa, sb, t, z, z, cs0_ref, cs1_ref, cv0_ref, cv1_ref,
        cw_t_ref, xm_ref, tr_ref, n)
    sa_ref[0] = sa
    sb_ref[0] = sb
    va_ref[0] = va
    vb_ref[0] = vb


def narrow_state_walk_pallas(
    rk2,      # int32 [15, 128, 2]   bit-major round keys (ciphers 0, 17)
    s0a, s0b,  # int32 [K, 128, 1]   seed planes per narrow block
    cs0, cs1,  # int32 [K, k, 128, 1]  CW seed planes, levels 0..k-1
    cv0, cv1,  # int32 [K, k, 128, 1]  CW value planes
    cw_t,     # int32 [K, k, 2]      (tl, tr) 0/-1
    x_mask,   # int32 [1, k, 1, W]   walk-order bit masks for the 2^k
              #                      node prefixes (frontier-position
              #                      enumeration, shared across keys)
    *,
    b: int,
    tile_words: int = 128,
    interpret: bool = False,
):
    """Walk the top k levels for every frontier node prefix, emitting the
    RAW carry instead of y: returns (sa, sb, va, vb [K, 128, W] planes,
    trajectory [K, k+1, W]) — the frontier-build half of the hybrid
    prefix path (key material, off the eval clock)."""
    k_num = s0a.shape[0]
    n = cs0.shape[1]
    w = x_mask.shape[3]
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"node words {w} not a multiple of tile {wt}")

    grid = (k_num, w // wt)
    keyed = pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0))
    level_spec = pl.BlockSpec((1, n, 128, 1), lambda k, j: (k, 0, 0, 0))
    state_out = pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j))
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_state_kernel, b=b, n=n, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, n + 1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda k, j: (0, 0, 0)),
            keyed, keyed,
            level_spec, level_spec, level_spec, level_spec,
            pl.BlockSpec((1, n, 2), lambda k, j: (k, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n, 1, wt), lambda k, j: (0, 0, 0, j)),
        ],
        out_specs=(
            state_out, state_out, state_out, state_out,
            pl.BlockSpec((1, n + 1, wt), lambda k, j: (k, 0, j)),
        ),
        interpret=interpret,
    )(rk2, s0a, s0b, cs0, cs1, cv0, cv1, cw_t, x_mask)


def _eval_kernel(rk2_ref, rows_ref, t0_ref, cs0_ref, cs1_ref, cv0_ref,
                 cv1_ref, np1a_ref, np1b_ref, cw_t_ref, xm_ref,
                 y0_ref, y1_ref, tr_ref, *, n_rem: int, interpret: bool):
    wt = xm_ref.shape[3]
    aes = make_narrow_aes(rk2_ref, wt, interpret)

    blk = rows_ref[0]  # [16, 32, wt]: sa|sb|va|vb, 4 int32 columns each
    sa = rows_to_state_planes(jnp, blk[0:4])
    sb = rows_to_state_planes(jnp, blk[4:8])
    va = rows_to_state_planes(jnp, blk[8:12])
    vb = rows_to_state_planes(jnp, blk[12:16])
    t = t0_ref[0]  # [1, wt] packed depth-k carry bits

    sa, sb, t, va, vb = narrow_walk_levels(
        aes, sa, sb, t, va, vb, cs0_ref, cs1_ref, cv0_ref, cv1_ref,
        cw_t_ref, xm_ref, tr_ref, n_rem)
    y0_ref[0] = va ^ sa ^ (np1a_ref[0] & t)
    y1_ref[0] = vb ^ sb ^ (np1b_ref[0] & t)


def dcf_hybrid_prefix_pallas(
    rk2,       # int32 [15, 128, 2]      bit-major round keys (0, 17)
    rows,      # int32 [K, 16, 32, W]    gathered state rows, j-reversed
               #                         tile layout (ops.pallas_prefix
               #                         module docstring); columns
               #                         0-3 sa, 4-7 sb, 8-11 va, 12-15 vb
    t0_pm,     # int32 [K, 1, W]         packed depth-k carry t bits
    cs0, cs1,  # int32 [K, n_rem, 128, 1]  CW planes for levels k..n-1
    cv0, cv1,  # int32 [K, n_rem, 128, 1]
    np1a, np1b,  # int32 [K, 128, 1]     final CW planes per block
    cw_t,      # int32 [K, n_rem, 2]
    x_mask,    # int32 [1, n_rem, 1, W]  lane masks for levels k..n-1
    *,
    tile_words: int = 128,
    interpret: bool = False,
):
    """Walk the remaining n-k narrow levels from gathered frontier
    carries.  Party is implicit (the frontier rows were expanded from the
    party's key share).  Returns (y_block0 [K, 128, W], y_block1
    [K, 128, W], remaining trajectory [K, n_rem+1, W]) — same layouts as
    ``dcf_narrow_walk_pallas``; the trajectory's first entry is the
    depth-k gate (== the gathered carry t), its last the final bit."""
    k_num = rows.shape[0]
    n_rem = cs0.shape[1]
    w = x_mask.shape[3]
    wt = min(tile_words, w)
    if w % wt != 0:
        raise ShapeError(f"point words {w} not a multiple of tile {wt}")

    grid = (k_num, w // wt)
    keyed = pl.BlockSpec((1, 128, 1), lambda k, j: (k, 0, 0))
    level_spec = pl.BlockSpec((1, n_rem, 128, 1),
                              lambda k, j: (k, 0, 0, 0))
    state_out = pl.BlockSpec((1, 128, wt), lambda k, j: (k, 0, j))
    params = (dict() if interpret else dict(
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024)))
    return pl.pallas_call(
        partial(_eval_kernel, n_rem=n_rem, interpret=interpret),
        **params,
        out_shape=(
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, 128, w), jnp.int32),
            jax.ShapeDtypeStruct((k_num, n_rem + 1, w), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((15, 128, 2), lambda k, j: (0, 0, 0)),
            pl.BlockSpec((1, 16, 32, wt), lambda k, j: (k, 0, 0, j)),
            pl.BlockSpec((1, 1, wt), lambda k, j: (k, 0, j)),
            level_spec, level_spec, level_spec, level_spec,
            keyed, keyed,
            pl.BlockSpec((1, n_rem, 2), lambda k, j: (k, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n_rem, 1, wt), lambda k, j: (0, 0, 0, j)),
        ],
        out_specs=(
            state_out, state_out,
            pl.BlockSpec((1, n_rem + 1, wt), lambda k, j: (k, 0, j)),
        ),
        interpret=interpret,
    )(rk2, rows, t0_pm, cs0, cs1, cv0, cv1, np1a, np1b, cw_t, x_mask)
