"""Crypto primitives: AES-256 and the Hirose PRG, vectorized.

- ``dcf_tpu.ops.aes`` — numpy batch AES-256 (host)
- ``dcf_tpu.ops.prg`` — numpy batch Hirose PRG (host)
- ``dcf_tpu.ops.aes_jax`` — JAX AES-256 for the TPU eval path
"""
